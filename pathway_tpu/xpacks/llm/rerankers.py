"""Rerankers (reference: xpacks/llm/rerankers.py — LLMReranker:59,
CrossEncoderReranker:159, EncoderReranker:224, FlashRankReranker:292,
rerank_topk_filter:16).

`CrossEncoderReranker` / `EncoderReranker` run on TPU via the flax encoder."""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu.reducers  # noqa: F401
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF


def rerank_topk_filter(
    docs: ColumnExpression, scores: ColumnExpression, k: int = 5
) -> ColumnExpression:
    """Keep the k best docs by reranker score
    (reference: rerankers.py:16). Returns (docs_tuple, scores_tuple)."""

    def filt(docs_v, scores_v) -> tuple:
        pairs = sorted(
            zip(docs_v, scores_v), key=lambda p: -float(p[1])
        )[: int(k)]
        if not pairs:
            return ((), ())
        d, s = zip(*pairs)
        return (tuple(d), tuple(s))

    return apply_with_type(filt, tuple, docs, scores)


class CrossEncoderReranker(UDF):
    """Query/doc pair scoring with a TPU cross-encoder
    (reference: rerankers.py:159 — torch CrossEncoder on CPU)."""

    def __init__(
        self,
        model_name: str = "pathway-tpu/cross-encoder",
        *,
        dim: int = 256,
        depth: int = 4,
        heads: int = 4,
        max_len: int = 512,
        mesh: Any = None,
        **kwargs,
    ):
        from pathway_tpu.xpacks.llm._encoder import EncoderRuntime
        from pathway_tpu.xpacks.llm._tokenizer import HashingTokenizer

        self.tokenizer = HashingTokenizer()
        self.runtime = EncoderRuntime(
            vocab_size=self.tokenizer.vocab_size,
            dim=dim,
            depth=depth,
            heads=heads,
            max_len=max_len,
            mesh=mesh,
            cross_encoder=True,
        )
        super().__init__(return_type=float, deterministic=True)
        self._prepare(self._score)
        self._batched = True
        self._fn = self._score_batch

    def _pair_text(self, doc: Any, query: str) -> str:
        if isinstance(doc, dict):
            doc = doc.get("text", str(doc))
        return f"{query} [SEP] {doc}"

    def _score_batch(self, docs: list, queries: list) -> list[float]:
        texts = [self._pair_text(d, q) for d, q in zip(docs, queries)]
        ids, mask = self.tokenizer.encode_batch(texts, self.runtime.max_len)
        out = self.runtime.forward_ids(ids, mask)
        return [float(x) for x in out]

    def _score(self, doc: Any, query: str, **kwargs) -> float:
        return self._score_batch([doc], [query])[0]

    @property
    def func(self):
        return self._score

    def __call__(self, doc: Any, query: Any, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class EncoderReranker(UDF):
    """Bi-encoder similarity reranker (reference: rerankers.py:224)."""

    def __init__(self, model_name: str = "pathway-tpu/minilm-384", **kwargs):
        from pathway_tpu.xpacks.llm.embedders import (
            SentenceTransformerEmbedder,
        )

        self.embedder = SentenceTransformerEmbedder(model=model_name, **kwargs)
        super().__init__(return_type=float, deterministic=True)
        self._prepare(self._score)

    def _score(self, doc: Any, query: str, **kwargs) -> float:
        if isinstance(doc, dict):
            doc = doc.get("text", str(doc))
        a = self.embedder._embed_batch([str(doc), str(query)])
        return float(np.dot(a[0], a[1]))

    @property
    def func(self):
        return self._score

    def __call__(self, doc: Any, query: Any, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class LLMReranker(UDF):
    """LLM-as-judge 1-5 relevance scoring (reference: rerankers.py:59)."""

    def __init__(self, llm: Any, **kwargs):
        self.llm = llm
        super().__init__(return_type=float)
        self._prepare(self._score)

    def _score(self, doc: Any, query: str, **kwargs) -> float:
        from pathway_tpu.xpacks.llm._utils import _coerce_sync

        prompt = (
            "Rate the relevance of the document to the query on a scale "
            f"1-5. Respond with a number only.\nQuery: {query}\nDoc: {doc}"
        )
        out = _coerce_sync(self.llm.func)(prompt)
        import json as _json
        import re

        try:
            parsed = _json.loads(str(out))
            if isinstance(parsed, dict) and "score" in parsed:
                return float(parsed["score"])
        except (ValueError, TypeError):
            pass
        m = re.search(r"\d+(\.\d+)?", str(out))
        if not m:
            raise ValueError(f"LLM reranker returned no number: {out!r}")
        return float(m.group())

    @property
    def func(self):
        return self._score

    def __call__(self, doc: Any, query: Any, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class FlashRankReranker(UDF):
    """(reference: rerankers.py:292) — gated on `flashrank`."""

    def __init__(self, model: str = "ms-marco-TinyBERT-L-2-v2", **kwargs):
        super().__init__(return_type=float)
        self._prepare(self._score)

    def _score(self, doc: Any, query: str, **kwargs) -> float:
        try:
            from flashrank import Ranker  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError(
                "FlashRankReranker requires `flashrank`; "
                "CrossEncoderReranker runs on TPU without extra deps"
            ) from exc
        raise NotImplementedError

    @property
    def func(self):
        return self._score
