"""RAG question answering (reference: xpacks/llm/question_answering.py —
answer_with_geometric_rag_strategy:97, BaseRAGQuestionAnswerer:314,
AdaptiveRAGQuestionAnswerer:638, DeckRetriever:761, RAGClient:879)."""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import pathway_tpu as pw
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.schema import column_definition
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import right, this
from pathway_tpu.xpacks.llm import prompts as prompt_lib


def answer_with_geometric_rag_strategy(
    questions: Sequence[str] | Any,
    documents: Sequence[Any],
    llm_chat_model: Any,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    strict_prompt: bool = False,
) -> str | None:
    """Adaptive document-count loop: ask with n docs; if the model answers
    'no information', retry with n*factor docs
    (reference: question_answering.py:97-162)."""
    question = questions if isinstance(questions, str) else questions[0]
    n = n_starting_documents
    # strict mode instructs small open-source models to answer tersely
    # with the exact not-found marker (reference: strict_prompt on
    # answer_with_geometric_rag_strategy, question_answering.py:120)
    rules = (
        "Answer with ONLY the shortest possible phrase, or exactly "
        '"No information found." if the documents do not contain the '
        "answer."
        if strict_prompt
        else ""
    )
    for _ in range(max_iterations):
        docs = list(documents)[:n]
        prompt = prompt_lib.prompt_qa_geometric_rag(
            question, docs, additional_rules=rules
        )
        answer = llm_chat_model.func(prompt)
        if answer and "no information" not in str(answer).lower():
            return str(answer)
        if n >= len(documents):
            break
        n *= factor
    return None


def answer_with_geometric_rag_strategy_from_index(
    questions: Any,  # ColumnReference[str]
    index: Any,  # DataIndex
    documents_column: str | Any,
    llm_chat_model: Any,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    metadata_filter: Any = None,
    strict_prompt: bool = False,
):
    """Table-level adaptive RAG straight from a DataIndex: retrieve the
    maximum document count once (n_starting * factor^(max_iterations-1)),
    then per row grow the prompt's document slice geometrically until the
    LLM finds an answer (reference: question_answering.py:162-215).
    Returns a column of answers (None where no answer was found)."""
    from pathway_tpu.internals import expression as expr_mod

    max_documents = n_starting_documents * (factor ** (max_iterations - 1))
    if isinstance(documents_column, expr_mod.ColumnReference):
        documents_column_name = documents_column.name
    else:
        documents_column_name = documents_column

    query_context = questions.table + index.query_as_of_now(
        questions,
        number_of_matches=max_documents,
        collapse_rows=True,
        metadata_filter=metadata_filter,
    ).select(
        documents_list=pw.coalesce(pw.this[documents_column_name], ()),
    )

    question_col = query_context[questions.name]
    llm = llm_chat_model

    def adaptive(question: str, docs: Any) -> str | None:
        doc_list = docs.value if isinstance(docs, Json) else list(docs or ())
        return answer_with_geometric_rag_strategy(
            question,
            list(doc_list or ()),
            llm,
            n_starting_documents,
            factor,
            max_iterations,
            strict_prompt=strict_prompt,
        )

    answered = query_context.select(
        answer=apply_with_type(
            adaptive, str | None, question_col, this.documents_list
        )
    )
    return answered.answer


def _validate_prompt_template(template: str) -> None:
    """A string prompt template must use exactly the {context} and {query}
    placeholders (reference: BaseRAGQuestionAnswerer template check)."""
    import string as _string

    fields = {
        f
        for _, f, _, _ in _string.Formatter().parse(template)
        if f is not None
    }
    if fields != {"context", "query"}:
        raise ValueError(
            "prompt_template must contain exactly the {context} and "
            f"{{query}} placeholders, got {sorted(fields)!r}"
        )


def _get_prompt_udf(prompt_template):
    """Normalize a str/callable/UDF prompt template into a
    (query, context) -> prompt UDF."""
    from pathway_tpu.internals.udfs import UDF as _UDF, udf as _udf

    if prompt_template is None:
        def default_prompt(query: str, context: str) -> str:
            # the packaged QA prompt over the joined context (keeps
            # self.prompt_template and the applied prompt in agreement)
            return prompt_lib.prompt_qa(query, [context])

        return _udf(default_prompt)
    if isinstance(prompt_template, str):
        _validate_prompt_template(prompt_template)
        template = prompt_template

        def fmt(query: str, context: str) -> str:
            return template.format(context=context, query=query)

        return _udf(fmt)
    if isinstance(prompt_template, _UDF):
        return prompt_template
    if callable(prompt_template):
        return _udf(prompt_template)
    raise ValueError(
        f"prompt_template must be a string, callable or UDF, got "
        f"{type(prompt_template)!r}"
    )


class BaseQuestionAnswerer:
    AnswerQuerySchema: Any
    RetrieveQuerySchema: Any
    StatisticsQuerySchema: Any
    InputsQuerySchema: Any


class BaseRAGQuestionAnswerer(BaseQuestionAnswerer):
    """retrieve → build prompt → LLM → answer
    (reference: question_answering.py:314)."""

    def __init__(
        self,
        llm: Any,
        indexer: Any,  # VectorStoreServer | DocumentStore
        *,
        default_llm_name: str | None = None,
        prompt_template: str | Callable[[str, str], str] | Any | None = None,
        summarize_template: Callable | None = None,
        search_topk: int = 6,
    ):
        self.llm = llm
        self.indexer = indexer
        self.default_llm_name = default_llm_name
        self.search_topk = search_topk
        self.prompt_template = prompt_template or prompt_lib.prompt_qa
        # normalized (query, context)->prompt UDF (reference: prompt_udf;
        # string templates validate their placeholders at construction)
        self.prompt_udf = _get_prompt_udf(prompt_template)
        self.summarize_template = summarize_template or prompt_lib.prompt_summarize
        self.server: Any = None
        self._pending_endpoints: list = []

        class AnswerQuerySchema(pw.Schema):
            prompt: str
            filters: str | None = column_definition(default_value=None, dtype=str)
            model: str | None = column_definition(default_value=None, dtype=str)
            return_context_docs: bool = column_definition(
                default_value=False, dtype=bool
            )

        class SummarizeQuerySchema(pw.Schema):
            text_list: Json
            model: str | None = column_definition(default_value=None, dtype=str)

        self.AnswerQuerySchema = AnswerQuerySchema
        self.SummarizeQuerySchema = SummarizeQuerySchema
        self.RetrieveQuerySchema = indexer.RetrieveQuerySchema
        self.StatisticsQuerySchema = indexer.StatisticsQuerySchema
        self.InputsQuerySchema = indexer.InputsQuerySchema

    # --- table-level flows ----------------------------------------------------

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """reference: BaseRAGQuestionAnswerer.answer_query"""
        retrieve_queries = pw_ai_queries.select(
            query=this.prompt,
            k=self.search_topk,
            metadata_filter=this.filters,
            filepath_globpattern=None,
        )
        retrieved = self.indexer.retrieve_query(retrieve_queries)
        combined = pw_ai_queries.with_columns(
            docs=retrieved.with_universe_of(pw_ai_queries).result
        )
        prompt_udf = self.prompt_udf
        llm = self.llm

        def build_prompt(prompt: str, docs: Json) -> str:
            from pathway_tpu.xpacks.llm._utils import _coerce_sync, _unwrap_udf
            from pathway_tpu.xpacks.llm.prompts import _doc_text

            doc_list = docs.value if isinstance(docs, Json) else list(docs or [])
            context = "\n\n".join(_doc_text(d) for d in (doc_list or []))
            fn = _coerce_sync(_unwrap_udf(prompt_udf))
            # dispatch on the template's own signature, so an internal
            # TypeError is never masked by a retry
            import inspect as _inspect

            try:
                sig_params = _inspect.signature(fn).parameters
                params = list(sig_params)
                has_var_kw = any(
                    p.kind is _inspect.Parameter.VAR_KEYWORD
                    for p in sig_params.values()
                )
            except (TypeError, ValueError):
                params, has_var_kw = [], False
            if "context" in params or has_var_kw:
                return str(fn(query=prompt, context=context))
            if len(params) >= 2 and params[1] in ("docs", "documents"):
                # legacy (query, docs) templates receive the list
                return str(fn(prompt, doc_list or []))
            return str(fn(prompt, context))

        with_prompt = combined.with_columns(
            _full_prompt=apply_with_type(
                build_prompt, str, this.prompt, this.docs
            )
        )
        # the chat receives role/content messages plus the query's model
        # (falling back to default_llm_name) — reference:
        # llm(prompt_chat_single_qa(...), model=coalesce(model, default))
        def to_messages(p: str):
            return ({"role": "system", "content": p},)

        msgs = apply_with_type(to_messages, Json, this._full_prompt)
        default_name = self.default_llm_name
        if default_name is not None:
            from pathway_tpu.internals.common import coalesce as _coalesce

            model_expr = _coalesce(this.model, default_name)
        else:
            model_expr = this.model
        answered = with_prompt.with_columns(
            response=llm(msgs, model=model_expr)
        )

        def fmt(response, docs, return_context_docs) -> Json:
            out: dict[str, Any] = {"response": response}
            if return_context_docs:
                out["context_docs"] = (
                    docs.value if isinstance(docs, Json) else docs
                )
            return Json(out)

        return answered.select(
            result=apply_with_type(
                fmt, Json, this.response, this.docs, this.return_context_docs
            )
        )

    # alias used by reference servers
    pw_ai_query = answer_query

    def summarize_query(self, summarize_queries: Table) -> Table:
        template = self.summarize_template
        llm = self.llm

        def build(text_list: Json) -> str:
            from pathway_tpu.xpacks.llm._utils import _coerce_sync, _unwrap_udf

            tl = text_list.value if isinstance(text_list, Json) else text_list
            return str(_coerce_sync(_unwrap_udf(template))(tl or []))

        with_prompt = summarize_queries.with_columns(
            _prompt=apply_with_type(build, str, this.text_list)
        )

        def to_messages(p: str):
            return ({"role": "system", "content": p},)

        msgs = apply_with_type(to_messages, Json, this._prompt)
        default_name = self.default_llm_name
        if default_name is not None:
            from pathway_tpu.internals.common import coalesce as _coalesce

            model_expr = _coalesce(this.model, default_name)
        else:
            model_expr = this.model
        answered = with_prompt.with_columns(
            response=llm(msgs, model=model_expr)
        )
        # the summarize result is the response STRING (reference:
        # summarize_query result column)
        return answered.select(result=this.response)

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    # --- serving -------------------------------------------------------------

    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        """Register the RAG REST endpoints
        (reference: question_answering.py build_server)."""
        from pathway_tpu.io.http import PathwayWebserver, rest_connector

        webserver = PathwayWebserver(host=host, port=port)
        self.server = webserver

        def serve(route, schema, handler):
            queries, writer = rest_connector(
                webserver=webserver,
                route=route,
                schema=schema,
                methods=("POST",),
                delete_completed_queries=True,
            )
            result = handler(queries)
            writer(result.select(query_id=result.id, result=result.result))

        serve("/v1/pw_ai_answer", self.AnswerQuerySchema, self.answer_query)
        serve(
            "/v1/pw_ai_summary", self.SummarizeQuerySchema, self.summarize_query
        )
        serve("/v2/answer", self.AnswerQuerySchema, self.answer_query)
        serve("/v2/summarize", self.SummarizeQuerySchema, self.summarize_query)

        def wrap_result(handler):
            def inner(queries):
                out = handler(queries)
                return out

            return inner

        from pathway_tpu.internals.common import apply_with_type as awt

        def retrieve_handler(queries):
            return self.indexer.retrieve_query(queries)

        def statistics_handler(queries):
            return self.indexer.statistics_query(queries)

        def inputs_handler(queries):
            return self.indexer.inputs_query(queries)

        serve("/v1/retrieve", self.RetrieveQuerySchema, retrieve_handler)
        serve("/v2/list_documents", self.InputsQuerySchema, inputs_handler)
        serve("/v1/statistics", self.StatisticsQuerySchema, statistics_handler)
        serve("/v1/pw_list_documents", self.InputsQuerySchema, inputs_handler)

    def run_server(
        self,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        threaded: bool = False,
        **kwargs,
    ):
        def run():
            pw.run(terminate_on_error=terminate_on_error)

        if threaded:
            t = threading.Thread(target=run, daemon=True, name="RAGServer")
            t.start()
            return t
        run()


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Geometrically grow the retrieved-docs count until the LLM finds an
    answer (reference: question_answering.py:638)."""

    def __init__(
        self,
        llm: Any,
        indexer: Any,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, pw_ai_queries: Table) -> Table:
        max_docs = self.n_starting_documents * (
            self.factor ** (self.max_iterations - 1)
        )
        retrieve_queries = pw_ai_queries.select(
            query=this.prompt,
            k=max_docs,
            metadata_filter=this.filters,
            filepath_globpattern=None,
        )
        retrieved = self.indexer.retrieve_query(retrieve_queries)
        combined = pw_ai_queries.with_columns(
            docs=retrieved.with_universe_of(pw_ai_queries).result
        )
        llm = self.llm
        n0, factor, iters = (
            self.n_starting_documents,
            self.factor,
            self.max_iterations,
        )

        def adaptive(prompt: str, docs: Json) -> Json:
            doc_list = docs.value if isinstance(docs, Json) else list(docs or [])
            answer = answer_with_geometric_rag_strategy(
                prompt, doc_list or [], llm, n0, factor, iters
            )
            return Json({"response": answer})

        return combined.select(
            result=apply_with_type(adaptive, Json, this.prompt, this.docs)
        )


class DeckRetriever(BaseRAGQuestionAnswerer):
    """Slide-deck search app (reference: question_answering.py:761)."""


class RAGClient:
    """HTTP client for the RAG REST API (reference: question_answering.py:879)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int = 90,
        additional_headers: dict | None = None,
    ):
        if url is None:
            url = f"http://{host}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.headers = additional_headers or {}

    def _post(self, route: str, payload: dict):
        import requests

        resp = requests.post(
            f"{self.url}{route}",
            json=payload,
            headers=self.headers,
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()

    def answer(self, prompt: str, filters: str | None = None, **kwargs):
        return self._post(
            "/v2/answer", {"prompt": prompt, "filters": filters, **kwargs}
        )

    pw_ai_answer = answer

    def summarize(self, text_list: list[str], **kwargs):
        return self._post("/v2/summarize", {"text_list": text_list, **kwargs})

    pw_ai_summary = summarize

    def retrieve(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    def statistics(self):
        return self._post("/v1/statistics", {})

    def list_documents(self, filters: str | None = None, keys: list | None = None):
        return self._post("/v2/list_documents", {"metadata_filter": filters})

    pw_list_documents = list_documents
