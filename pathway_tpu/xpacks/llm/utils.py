"""Public LLM-xpack utilities (reference:
python/pathway/xpacks/llm/utils.py — combine_metadata)."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.xpacks.llm._utils import _is_text_with_meta, _to_dict


def combine_metadata(
    table,
    from_column="text",
    to_column="metadata",
    clean_from_column: bool = True,
):
    """Move the metadata half of (text, metadata) tuples in `from_column`
    into `to_column` (merging with any existing dict there, creating the
    column if absent); optionally strip `from_column` down to the text."""

    @pw.udf
    def move_metadata(text_with_meta, metadata) -> dict:
        if _is_text_with_meta(text_with_meta):
            return {**_to_dict(metadata), **_to_dict(text_with_meta[1])}
        return metadata

    @pw.udf
    def clean_metadata(text_with_meta) -> str:
        if _is_text_with_meta(text_with_meta):
            return text_with_meta[0]
        if isinstance(text_with_meta, str):
            return text_with_meta
        raise ValueError(
            "Expected string or tuple with string and dict, got "
            f"{text_with_meta}"
        )

    from_column_ref = (
        table[from_column] if isinstance(from_column, str) else from_column
    )
    if isinstance(to_column, str):
        if to_column not in table.column_names():
            table += table.select(**{to_column: dict()})
        to_column_ref = table[to_column]
    else:
        to_column_ref = to_column

    table = table.with_columns(
        **{
            to_column_ref.name: move_metadata(from_column_ref, to_column_ref),
            from_column_ref.name: (
                clean_metadata(from_column_ref)
                if clean_from_column
                else from_column_ref
            ),
        }
    )

    return table
