"""Prompt templates & builders (reference: xpacks/llm/prompts.py)."""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.json import Json


def prompt_short_qa(context: str, query: str) -> str:
    return (
        "Please provide an answer based solely on the provided sources. "
        "Keep your answer concise and accurate.\n"
        f"Sources:\n{context}\n"
        f"Question: {query}\nAnswer:"
    )


def prompt_qa(
    query: str,
    docs: Sequence[Any],
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> str:
    ctx = "\n\n".join(_doc_text(d) for d in docs)
    return (
        "Use the below articles to answer the subsequent question. If the "
        "answer cannot be found in the articles, write "
        f'"{information_not_found_response}".{additional_rules}\n'
        f"Articles:\n{ctx}\n"
        f"Question: {query}\nAnswer:"
    )


def prompt_qa_geometric_rag(
    query: str,
    docs: Sequence[Any],
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> str:
    return prompt_qa(query, docs, information_not_found_response, additional_rules)


def prompt_summarize(text_list: Sequence[str]) -> str:
    joined = "\n".join(str(t) for t in text_list)
    return (
        "Summarize the following documents into one concise summary.\n"
        f"{joined}\nSummary:"
    )


def prompt_query_rewrite(query: str, docs: Sequence[Any] = ()) -> str:
    return (
        "Rewrite the following query to be clearer and more specific for "
        f"retrieval.\nQuery: {query}\nRewritten query:"
    )


def prompt_query_rewrite_hyde(query: str) -> str:
    return (
        "Write a short hypothetical passage that would answer the query "
        f"(HyDE).\nQuery: {query}\nPassage:"
    )


def _doc_text(d: Any) -> str:
    if isinstance(d, Json):
        d = d.value
    if isinstance(d, dict):
        return str(d.get("text", d))
    return str(d)
