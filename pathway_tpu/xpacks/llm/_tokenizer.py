"""Deterministic hashing tokenizer for the local TPU encoder.

No vocabulary files / no network: tokens are hashed into a fixed id space
(feature-hashing). If a HuggingFace tokenizer is locally cached, it can be
plugged in instead (`HFTokenizerAdapter`)."""

from __future__ import annotations

import hashlib
import re
import struct
from typing import Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[a-zA-Z]+|\d+|[^\sa-zA-Z\d]", re.UNICODE)

PAD_ID = 0
CLS_ID = 1
_RESERVED = 2


class HashingTokenizer:
    def __init__(self, vocab_size: int = 30522, lowercase: bool = True):
        self.vocab_size = vocab_size
        self.lowercase = lowercase

    def _hash(self, token: str) -> int:
        h = struct.unpack(
            "<Q", hashlib.blake2b(token.encode(), digest_size=8).digest()
        )[0]
        return _RESERVED + (h % (self.vocab_size - _RESERVED))

    def tokenize(self, text: str) -> list[str]:
        if self.lowercase:
            text = text.lower()
        return _TOKEN_RE.findall(text)

    def encode(self, text: str, max_len: int) -> list[int]:
        ids = [CLS_ID] + [self._hash(t) for t in self.tokenize(text)]
        return ids[:max_len]

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (ids [B, L], mask [B, L]) padded to the smallest
        power-of-two-ish bucket ≥ longest sequence (static shapes for jit)."""
        encoded = [self.encode(t, max_len) for t in texts]
        longest = max((len(e) for e in encoded), default=1)
        bucket = _bucket_len(longest, max_len)
        ids = np.full((len(texts), bucket), PAD_ID, dtype=np.int32)
        mask = np.zeros((len(texts), bucket), dtype=np.float32)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1.0
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self.tokenize(text))


def _bucket_len(n: int, max_len: int) -> int:
    # pad to {16, 32, 64, 128, ...} so jit compiles O(log max_len) variants
    b = 16
    while b < n:
        b *= 2
    return min(b, max_len)


class WordPieceTokenizer:
    """Real WordPiece over a local vocab.txt — the tokenization BERT/MiniLM
    checkpoints were trained with (reference embedders tokenize via the HF
    tokenizer inside sentence-transformers; this is the dependency-free
    equivalent, verified token-for-token against BertTokenizer in
    tests/test_bert_parity.py). Basic-tokenizer steps: clean, lowercase +
    strip accents (uncased models), CJK isolation, punctuation split; then
    greedy longest-match-first wordpiece with '##' continuations."""

    def __init__(
        self,
        vocab_file: str,
        lowercase: bool = True,
        max_word_chars: int = 100,
    ):
        import unicodedata

        self._ud = unicodedata
        self.vocab: dict[str, int] = {}
        with open(vocab_file, encoding="utf-8") as f:
            for i, line in enumerate(f):
                self.vocab[line.rstrip("\n")] = i
        self.vocab_size = len(self.vocab)
        self.lowercase = lowercase
        self.max_word_chars = max_word_chars
        missing = [
            tok for tok in ("[UNK]", "[CLS]", "[SEP]") if tok not in self.vocab
        ]
        if missing:
            # guessing ids here would silently produce garbage token
            # streams (ADVICE r2) — a BERT vocab without these is broken
            raise ValueError(
                f"vocab file {vocab_file!r} is missing required special "
                f"tokens {missing}"
            )
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.unk_id = self.vocab["[UNK]"]
        self.cls_id = self.vocab["[CLS]"]
        self.sep_id = self.vocab["[SEP]"]
        # BertTokenizer's never_split set: literal special tokens in the
        # text pass through un-lowercased and un-split
        self.special_tokens = {
            "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
        }

    # --- basic tokenization (mirrors BERT's BasicTokenizer) ---------------

    def _is_punct(self, ch: str) -> bool:
        cp = ord(ch)
        if (
            33 <= cp <= 47
            or 58 <= cp <= 64
            or 91 <= cp <= 96
            or 123 <= cp <= 126
        ):
            return True
        return self._ud.category(ch).startswith("P")

    def _is_cjk(self, ch: str) -> bool:
        cp = ord(ch)
        return (
            0x4E00 <= cp <= 0x9FFF
            or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF
            or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F
            or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF
            or 0x2F800 <= cp <= 0x2FA1F
        )

    def _basic_tokens(self, text: str) -> list[str]:
        # stage 1 — clean + CJK isolation (BertTokenizer._clean_text +
        # _tokenize_chinese_chars): \t\n\r are whitespace (NOT controls,
        # despite their Cc category); all other C* are stripped; Zs is the
        # only other whitespace class
        chars: list[str] = []
        for ch in text:
            cp = ord(ch)
            if ch in " \t\n\r":
                chars.append(" ")
                continue
            if cp == 0 or cp == 0xFFFD or self._ud.category(ch).startswith(
                "C"
            ):
                continue
            if self._ud.category(ch) == "Zs":
                chars.append(" ")
            elif self._is_cjk(ch):
                chars.extend((" ", ch, " "))
            else:
                chars.append(ch)
        # stage 2 — whitespace split, then per token: never_split check,
        # lowercase + accent strip, punctuation split
        out: list[str] = []
        for tok in "".join(chars).split():
            if tok in self.special_tokens:
                out.append(tok)
                continue
            if self.lowercase:
                tok = tok.lower()
                tok = "".join(
                    c
                    for c in self._ud.normalize("NFD", tok)
                    if self._ud.category(c) != "Mn"
                )
            buf: list[str] = []
            for ch in tok:
                if self._is_punct(ch):
                    if buf:
                        out.append("".join(buf))
                        buf.clear()
                    out.append(ch)
                else:
                    buf.append(ch)
            if buf:
                out.append("".join(buf))
        return out

    # --- wordpiece ---------------------------------------------------------

    def _wordpiece(self, word: str) -> list[int]:
        if len(word) > self.max_word_chars:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int) -> list[int]:
        ids = [self.cls_id]
        for word in self._basic_tokens(text):
            ids.extend(self._wordpiece(word))
            if len(ids) >= max_len - 1:
                break
        ids = ids[: max_len - 1]
        ids.append(self.sep_id)
        return ids

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        encoded = [self.encode(t, max_len) for t in texts]
        longest = max((len(e) for e in encoded), default=1)
        bucket = _bucket_len(longest, max_len)
        ids = np.full((len(texts), bucket), self.pad_id, dtype=np.int32)
        mask = np.zeros((len(texts), bucket), dtype=np.float32)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1.0
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self.encode(text, 1 << 30)) - 2


class HFTokenizerAdapter:
    """Wraps a locally-cached HuggingFace tokenizer (no downloads)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(
            name_or_path, local_files_only=True
        )
        self.vocab_size = self.tok.vocab_size

    def encode_batch(self, texts, max_len):
        out = self.tok(
            list(texts),
            truncation=True,
            max_length=max_len,
            padding=True,
            return_tensors="np",
        )
        ids = out["input_ids"].astype(np.int32)
        mask = out["attention_mask"].astype(np.float32)
        bucket = _bucket_len(ids.shape[1], max_len)
        if ids.shape[1] < bucket:
            pad = bucket - ids.shape[1]
            ids = np.pad(ids, ((0, 0), (0, pad)))
            mask = np.pad(mask, ((0, 0), (0, pad)))
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self.tok.encode(text))
