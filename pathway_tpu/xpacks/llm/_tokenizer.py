"""Deterministic hashing tokenizer for the local TPU encoder.

No vocabulary files / no network: tokens are hashed into a fixed id space
(feature-hashing). If a HuggingFace tokenizer is locally cached, it can be
plugged in instead (`HFTokenizerAdapter`)."""

from __future__ import annotations

import hashlib
import re
import struct
from typing import Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[a-zA-Z]+|\d+|[^\sa-zA-Z\d]", re.UNICODE)

PAD_ID = 0
CLS_ID = 1
_RESERVED = 2


class HashingTokenizer:
    def __init__(self, vocab_size: int = 30522, lowercase: bool = True):
        self.vocab_size = vocab_size
        self.lowercase = lowercase

    def _hash(self, token: str) -> int:
        h = struct.unpack(
            "<Q", hashlib.blake2b(token.encode(), digest_size=8).digest()
        )[0]
        return _RESERVED + (h % (self.vocab_size - _RESERVED))

    def tokenize(self, text: str) -> list[str]:
        if self.lowercase:
            text = text.lower()
        return _TOKEN_RE.findall(text)

    def encode(self, text: str, max_len: int) -> list[int]:
        ids = [CLS_ID] + [self._hash(t) for t in self.tokenize(text)]
        return ids[:max_len]

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (ids [B, L], mask [B, L]) padded to the smallest
        power-of-two-ish bucket ≥ longest sequence (static shapes for jit)."""
        encoded = [self.encode(t, max_len) for t in texts]
        longest = max((len(e) for e in encoded), default=1)
        bucket = _bucket_len(longest, max_len)
        ids = np.full((len(texts), bucket), PAD_ID, dtype=np.int32)
        mask = np.zeros((len(texts), bucket), dtype=np.float32)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1.0
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self.tokenize(text))


def _bucket_len(n: int, max_len: int) -> int:
    # pad to {16, 32, 64, 128, ...} so jit compiles O(log max_len) variants
    b = 16
    while b < n:
        b *= 2
    return min(b, max_len)


class HFTokenizerAdapter:
    """Wraps a locally-cached HuggingFace tokenizer (no downloads)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(
            name_or_path, local_files_only=True
        )
        self.vocab_size = self.tok.vocab_size

    def encode_batch(self, texts, max_len):
        out = self.tok(
            list(texts),
            truncation=True,
            max_length=max_len,
            padding=True,
            return_tensors="np",
        )
        ids = out["input_ids"].astype(np.int32)
        mask = out["attention_mask"].astype(np.float32)
        bucket = _bucket_len(ids.shape[1], max_len)
        if ids.shape[1] < bucket:
            pad = bucket - ids.shape[1]
            ids = np.pad(ids, ((0, 0), (0, pad)))
            mask = np.pad(mask, ((0, 0), (0, pad)))
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self.tok.encode(text))
