"""Document parsers (reference: python/pathway/xpacks/llm/parsers.py —
Utf8Parser:46, UnstructuredParser:82 with single/elements/paged/basic/
by_title chunking, DoclingParser:329, ImageParser:456, SlideParser:598,
PypdfParser:775).

Parsers are UDFs bytes -> list[tuple[str, dict]] (text, metadata). The
reference delegates partitioning to the `unstructured` library and
chunking to its chunk_elements/chunk_by_title. Here partitioning and all
five chunking modes are implemented NATIVELY (pure python — no optional
dependency needed for text/markdown/PDF-via-pypdf inputs); when the
`unstructured` library IS installed it is used for full-fidelity
partitioning of office formats, with the same chunking applied either way.
"""

from __future__ import annotations

import io
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Literal

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.udfs import UDF

ChunkingMode = Literal["single", "elements", "paged", "basic", "by_title"]


class Utf8Parser(UDF):
    """Decode bytes as UTF-8 (reference: parsers.py:46)."""

    def __init__(self, **kwargs):
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            return [(contents, {})]
        return [(contents.decode("utf-8", errors="replace"), {})]

    @property
    def func(self):
        return self.parse


ParseUtf8 = Utf8Parser


class PypdfParser(UDF):
    """PDF text extraction via pypdf (reference: parsers.py:775)."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        self.apply_text_cleanup = apply_text_cleanup
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        try:
            from pypdf import PdfReader  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("PypdfParser requires `pypdf`") from exc

        reader = PdfReader(io.BytesIO(contents))
        out = []
        for i, page in enumerate(reader.pages):
            text = page.extract_text() or ""
            if self.apply_text_cleanup:
                text = " ".join(text.split())
            out.append((text, {"page": i}))
        return out

    @property
    def func(self):
        return self.parse


# ---------------------------------------------------------------------------
# Native partitioning: bytes -> typed elements


@dataclass
class Element:
    """One partitioned document element (the `unstructured` Element
    analog: text + category + metadata incl. page_number)."""

    text: str
    category: str = "NarrativeText"
    metadata: dict = field(default_factory=dict)

    def __str__(self) -> str:
        return self.text


_LIST_RE = re.compile(r"^\s*([-*•]|\d+[.)])\s+")


def _looks_like_table(lines: list[str]) -> bool:
    if len(lines) < 2:
        return False
    piped = sum(1 for l in lines if l.count("|") >= 2)
    return piped >= max(2, len(lines) - 1)


def _table_to_html(lines: list[str]) -> str:
    rows = []
    for l in lines:
        cells = [c.strip() for c in l.strip().strip("|").split("|")]
        if all(re.fullmatch(r":?-{2,}:?", c or "--") for c in cells):
            continue  # markdown separator row
        rows.append("".join(f"<td>{c}</td>" for c in cells))
    return "<table>" + "".join(f"<tr>{r}</tr>" for r in rows) + "</table>"


def _classify_block(block: str) -> Element:
    lines = block.splitlines()
    stripped = block.strip()
    if _looks_like_table(lines):
        return Element(
            stripped, "Table", {"text_as_html": _table_to_html(lines)}
        )
    if _LIST_RE.match(stripped):
        return Element(stripped, "ListItem")
    first = lines[0].strip()
    if first.startswith("#"):
        return Element(stripped.lstrip("# ").strip(), "Title")
    if (
        len(lines) == 1
        and 0 < len(first) <= 80
        and not first.endswith((".", ",", ";", ":"))
        and (first.isupper() or first.istitle())
    ):
        return Element(stripped, "Title")
    return Element(stripped, "NarrativeText")


def _partition_text(text: str, page_number: int = 1) -> list[Element]:
    """Blank-line blocks classified into Title/ListItem/Table/Narrative;
    form feeds advance the page number."""
    out: list[Element] = []
    for page_offset, page in enumerate(text.split("\f")):
        pno = page_number + page_offset
        for block in re.split(r"\n\s*\n", page):
            if not block.strip():
                continue
            el = _classify_block(block)
            el.metadata.setdefault("page_number", pno)
            out.append(el)
    return out


def native_partition(
    contents: bytes, filename: str | None = None
) -> list[Element]:
    """bytes -> elements without optional dependencies: PDFs page by page
    via pypdf when available, everything else as (decoded) text."""
    if contents[:5] == b"%PDF-":
        try:
            from pypdf import PdfReader

            reader = PdfReader(io.BytesIO(contents))
            out: list[Element] = []
            for i, page in enumerate(reader.pages):
                out.extend(_partition_text(page.extract_text() or "", i + 1))
            return out
        except ImportError:
            pass
    try:
        text = contents.decode("utf-8")
    except UnicodeDecodeError:
        text = contents.decode("latin-1")
    return _partition_text(text)


# ---------------------------------------------------------------------------
# Native chunking (reference: unstructured.chunking basic/title)


def _merge_chunk_meta(left: dict, right: dict) -> dict:
    links = left.pop("links", []) + right.pop("links", [])
    languages = list(set(left.pop("languages", []) + right.pop("languages", [])))
    result = {**left, **right}
    if links:
        result["links"] = links
    if languages:
        result["languages"] = languages
    for k in ("coordinates", "parent_id", "category_depth", "category"):
        result.pop(k, None)
    return result


def chunk_elements_basic(
    elements: list[Element],
    max_characters: int = 500,
    new_after_n_chars: int | None = None,
    overlap: int = 0,
    **_kwargs: Any,
) -> list[Element]:
    """Pack consecutive elements into chunks of at most `max_characters`
    (soft-break after new_after_n_chars); oversized elements split hard
    with `overlap` characters carried between splits."""
    soft = new_after_n_chars or max_characters
    # an overlap >= max_characters would never shrink the remainder
    overlap = max(0, min(overlap, max_characters - 1))
    chunks: list[Element] = []
    cur_text: list[str] = []
    cur_meta: dict = {}
    cur_len = 0

    def flush():
        nonlocal cur_text, cur_meta, cur_len
        if cur_text:
            chunks.append(
                Element("\n\n".join(cur_text), "CompositeElement", cur_meta)
            )
        cur_text, cur_meta, cur_len = [], {}, 0

    for el in elements:
        text = el.text
        while len(text) > max_characters:
            flush()
            chunks.append(
                Element(
                    text[:max_characters], "CompositeElement", dict(el.metadata)
                )
            )
            start = max_characters - overlap if overlap else max_characters
            text = text[start:]
        if cur_len + len(text) + 2 > soft:
            flush()
        cur_text.append(text)
        cur_meta = _merge_chunk_meta(cur_meta, dict(el.metadata))
        cur_len += len(text) + 2
    flush()
    return chunks


def chunk_by_title(
    elements: list[Element],
    max_characters: int = 500,
    **kwargs: Any,
) -> list[Element]:
    """Like basic chunking, but a Title element always starts a new chunk
    (section-aware splitting, reference: unstructured chunk_by_title)."""
    sections: list[list[Element]] = []
    cur: list[Element] = []
    for el in elements:
        if el.category == "Title" and cur:
            sections.append(cur)
            cur = []
        cur.append(el)
    if cur:
        sections.append(cur)
    out: list[Element] = []
    for section in sections:
        out.extend(
            chunk_elements_basic(
                section, max_characters=max_characters, **kwargs
            )
        )
    return out


class UnstructuredParser(UDF):
    """Partition + chunk documents (reference: parsers.py:82).

    chunking_mode:
      - "single": whole document as one chunk
      - "elements": one chunk per partitioned element
      - "paged": one chunk per page
      - "basic": max_characters-packed chunks
      - "by_title": section-aware chunks starting at titles
    Partitioning uses the `unstructured` library when installed, else the
    native partitioner (text/markdown/PDF-via-pypdf)."""

    _CHUNKING_MODES = ("single", "elements", "paged", "basic", "by_title")

    def __init__(
        self,
        chunking_mode: ChunkingMode = "single",
        partition_kwargs: dict | None = None,
        post_processors: list[Callable] | None = None,
        chunking_kwargs: dict | None = None,
        mode: str | None = None,  # legacy alias for chunking_mode
        **kwargs: Any,
    ):
        if mode is not None:
            chunking_mode = mode  # type: ignore[assignment]
        self._validate_chunking_mode(chunking_mode)
        self.chunking_mode = chunking_mode
        self.partition_kwargs = partition_kwargs or {}
        self.post_processors = list(post_processors or [])
        self.chunking_kwargs = chunking_kwargs or {}
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    @classmethod
    def _validate_chunking_mode(cls, chunking_mode) -> None:
        if chunking_mode not in cls._CHUNKING_MODES:
            raise ValueError(
                f"Got {chunking_mode} for `chunking_mode`, but should be "
                f"one of `{cls._CHUNKING_MODES}`"
            )

    def _combine_metadata(self, left: dict, right: dict) -> dict:
        return _merge_chunk_meta(dict(left), dict(right))

    @staticmethod
    def _extract_element_meta(element) -> tuple[str, dict]:
        meta_obj = getattr(element, "metadata", None)
        if meta_obj is not None and not isinstance(meta_obj, dict):
            metadata = meta_obj.to_dict()
        else:
            metadata = dict(meta_obj or {})
        if getattr(element, "category", None):
            metadata["category"] = element.category
        return str(element), metadata

    def _as_native(self, elements: list) -> list[Element]:
        out = []
        for e in elements:
            text, meta = self._extract_element_meta(e)
            out.append(
                Element(text, meta.get("category", "NarrativeText"), meta)
            )
        return out

    def _partition(self, contents: bytes) -> list:
        try:
            from unstructured.partition.auto import (  # type: ignore[import-not-found]
                partition,
            )

            return partition(
                file=io.BytesIO(contents), **self.partition_kwargs
            )
        except ImportError:
            return native_partition(contents)

    def _chunk(
        self,
        elements: list,
        chunking_mode: ChunkingMode | None = None,
        chunking_kwargs: dict | None = None,
    ) -> list[tuple[str, dict]]:
        chunking_mode = chunking_mode or self.chunking_mode
        chunking_kwargs = {**self.chunking_kwargs, **(chunking_kwargs or {})}
        if chunking_mode == "basic":
            return [
                self._extract_element_meta(el)
                for el in chunk_elements_basic(
                    self._as_native(elements), **chunking_kwargs
                )
            ]
        if chunking_mode == "by_title":
            return [
                self._extract_element_meta(el)
                for el in chunk_by_title(
                    self._as_native(elements), **chunking_kwargs
                )
            ]
        if chunking_mode == "elements":
            return [self._extract_element_meta(el) for el in elements]
        if chunking_mode == "paged":
            text_by_page: dict[int, str] = {}
            meta_by_page: dict[int, dict] = {}
            for element in elements:
                text, metadata = self._extract_element_meta(element)
                page = metadata.get("page_number", 1)
                text_by_page[page] = text_by_page.get(page, "") + text + "\n\n"
                meta_by_page[page] = self._combine_metadata(
                    meta_by_page.get(page, {}), metadata
                )
            return [
                (text_by_page[p], meta_by_page[p]) for p in sorted(text_by_page)
            ]
        # single
        metadata: dict = {}
        for element in elements:
            metadata = self._combine_metadata(
                metadata, self._extract_element_meta(element)[1]
            )
        return [("\n\n".join(str(el) for el in elements), metadata)]

    def parse(
        self,
        contents: bytes,
        chunking_mode: ChunkingMode | None = None,
        **kwargs: Any,
    ) -> list[tuple[str, dict]]:
        elements = self._partition(contents)
        for post in self.post_processors:
            elements = [post(e) for e in elements]
        return self._chunk(
            elements, chunking_mode, kwargs.get("chunking_kwargs")
        )

    @property
    def func(self):
        return self.parse

    def __call__(self, contents: Any, **kwargs) -> expr_mod.ColumnExpression:
        return super().__call__(contents, **kwargs)


class ParseUnstructured(UnstructuredParser):
    def __init__(self, *args, **kwargs):
        import warnings

        warnings.warn(
            "This class is deprecated, use `UnstructuredParser` instead."
        )
        super().__init__(*args, **kwargs)


class DoclingParser(UnstructuredParser):
    """Markdown document conversion (reference: parsers.py:329). Uses
    `docling` when installed; otherwise converts natively partitioned
    elements to markdown (titles -> #, tables kept as pipes)."""

    def __init__(self, chunking_mode: ChunkingMode = "single", **kwargs):
        super().__init__(chunking_mode=chunking_mode, **kwargs)

    def _partition(self, contents: bytes) -> list:
        try:
            from docling.document_converter import (  # type: ignore[import-not-found]
                DocumentConverter,
            )

            conv = DocumentConverter()
            result = conv.convert(io.BytesIO(contents))
            md = result.document.export_to_markdown()
            return _partition_text(md)
        except ImportError:
            elements = native_partition(contents)
            for el in elements:
                if el.category == "Title" and not el.text.startswith("#"):
                    el.text = f"# {el.text}"
            return elements


class ImageParser(UDF):
    """Describe an image with a vision LLM (reference: parsers.py:456).
    `llm` is any callable/UDF taking (prompt, image_bytes) -> str; table
    and schema extraction ride the prompt."""

    DEFAULT_PROMPT = "Describe the contents of this image in detail."

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str | None = None,
        **kwargs: Any,
    ):
        self.llm = llm
        self.parse_prompt = parse_prompt or self.DEFAULT_PROMPT
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    def _describe(self, contents: bytes) -> str:
        if self.llm is None:
            raise ValueError(
                "ImageParser needs a vision `llm` callable "
                "(prompt, image_bytes) -> str"
            )
        from pathway_tpu.xpacks.llm._utils import _coerce_sync, _unwrap_udf

        return str(
            _coerce_sync(_unwrap_udf(self.llm))(self.parse_prompt, contents)
        )

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        return [(self._describe(contents), {"parser": "image"})]

    @property
    def func(self):
        return self.parse


class SlideParser(ImageParser):
    """Per-slide/page vision parsing (reference: parsers.py:598): PDFs are
    split into single-page documents, each one goes through the vision LLM
    separately, keeping page metadata."""

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        pages: list[bytes]
        if contents[:5] == b"%PDF-":
            try:
                from pypdf import PdfReader, PdfWriter

                reader = PdfReader(io.BytesIO(contents))
                pages = []
                for page in reader.pages:
                    writer = PdfWriter()
                    writer.add_page(page)
                    buf = io.BytesIO()
                    writer.write(buf)
                    pages.append(buf.getvalue())
            except ImportError:
                pages = [contents]
        else:
            pages = [contents]
        docs = []
        for i, page_bytes in enumerate(pages):
            docs.append(
                (
                    self._describe(page_bytes),
                    {"page_number": i + 1, "parser": "slide"},
                )
            )
        return docs
