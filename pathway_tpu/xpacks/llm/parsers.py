"""Document parsers (reference: xpacks/llm/parsers.py — Utf8:46,
Unstructured:82, Docling:329, ImageParser:456, SlideParser:598, Pypdf:775).

Parsers are UDFs bytes -> list[tuple[str, dict]] (text, metadata)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.udfs import UDF


class Utf8Parser(UDF):
    """Decode bytes as UTF-8 (reference: parsers.py:46 ParseUtf8)."""

    def __init__(self, **kwargs):
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            return [(contents, {})]
        return [(contents.decode("utf-8", errors="replace"), {})]

    @property
    def func(self):
        return self.parse


ParseUtf8 = Utf8Parser


class PypdfParser(UDF):
    """PDF text extraction via pypdf (reference: parsers.py:775)."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs):
        self.apply_text_cleanup = apply_text_cleanup
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        try:
            from pypdf import PdfReader  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("PypdfParser requires `pypdf`") from exc
        import io

        reader = PdfReader(io.BytesIO(contents))
        out = []
        for i, page in enumerate(reader.pages):
            text = page.extract_text() or ""
            if self.apply_text_cleanup:
                text = " ".join(text.split())
            out.append((text, {"page": i}))
        return out

    @property
    def func(self):
        return self.parse


class UnstructuredParser(UDF):
    """(reference: parsers.py:82) — requires `unstructured`."""

    def __init__(self, mode: str = "single", **kwargs):
        self.mode = mode
        super().__init__(return_type=list)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        try:
            from unstructured.partition.auto import partition  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError(
                "UnstructuredParser requires `unstructured`; "
                "Utf8Parser and PypdfParser work without extra deps"
            ) from exc
        import io

        elements = partition(file=io.BytesIO(contents))
        if self.mode == "single":
            return [("\n\n".join(str(e) for e in elements), {})]
        return [(str(e), {"category": e.category}) for e in elements]

    @property
    def func(self):
        return self.parse


class DoclingParser(UnstructuredParser):
    """(reference: parsers.py:329) — gated on `docling`."""

    def parse(self, contents: bytes, **kwargs):
        try:
            from docling.document_converter import DocumentConverter  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("DoclingParser requires `docling`") from exc
        raise NotImplementedError


class ImageParser(UDF):
    """Vision-LLM image description (reference: parsers.py:456)."""

    def __init__(self, llm: Any = None, prompt: str = "Describe the image.", **kwargs):
        self.llm = llm
        self.prompt = prompt
        super().__init__(return_type=list)
        self._prepare(self.parse)

    def parse(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        raise NotImplementedError(
            "ImageParser requires a vision LLM endpoint; configure `llm`"
        )

    @property
    def func(self):
        return self.parse


class SlideParser(ImageParser):
    """(reference: parsers.py:598)"""
