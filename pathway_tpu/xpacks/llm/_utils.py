"""Small UDF/async plumbing helpers shared across the LLM xpack
(reference: python/pathway/xpacks/llm/_utils.py)."""

from __future__ import annotations

import asyncio
import functools
import inspect
import threading
from collections.abc import Callable
from typing import Any

import pathway_tpu as pw


class _RunThread(threading.Thread):
    """Run a coroutine on a fresh loop when one is already running here."""

    def __init__(self, coroutine):
        self.coroutine = coroutine
        self.result = None
        super().__init__()

    def run(self):
        self.result = asyncio.run(self.coroutine)


def _run_async(coroutine):
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop and loop.is_running():
        thread = _RunThread(coroutine)
        thread.start()
        thread.join()
        return thread.result
    return asyncio.run(coroutine)


def _coerce_sync(func: Callable) -> Callable:
    if asyncio.iscoroutinefunction(func):

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            return _run_async(func(*args, **kwargs))

        return wrapper
    return func


def _extract_value(data: Any) -> Any:
    if isinstance(data, pw.Json):
        return data.value
    return data


def _unwrap_udf(func) -> Callable:
    """Turn a UDF into its plain callable (keeps UDF-applied settings)."""
    if isinstance(func, pw.UDF):
        return func.func
    return func


def _wrap_udf(func):
    """Wrap a callable into a UDF (UDFs pass through)."""
    if isinstance(func, pw.UDF):
        return func
    return pw.udf(func)


def get_func_arg_names(func):
    sig = inspect.signature(func)
    return [param.name for param in sig.parameters.values()]


def _is_text_with_meta(text_with_meta) -> bool:
    return (
        isinstance(text_with_meta, tuple)
        and len(text_with_meta) == 2
        and (
            isinstance(text_with_meta[1], dict)
            or isinstance(text_with_meta[1], pw.Json)
        )
    )


def _to_dict(element):
    if isinstance(element, pw.Json):
        return element.as_dict()
    return element
