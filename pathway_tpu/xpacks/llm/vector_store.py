"""VectorStoreServer — live document index + REST retrieval
(reference: xpacks/llm/vector_store.py:39 — _build_graph:227-309,
retrieve/statistics/inputs queries:311-500, VectorStoreClient:651).

The document side (parse → post-process → split → embed → index) runs on TPU
through the batched embedder; retrieval is the on-chip dense top-k."""

from __future__ import annotations

import json as _json
import threading
from typing import Any, Callable, Iterable, Sequence

import numpy as np

import pathway_tpu as pw
import pathway_tpu.reducers as reducers
from pathway_tpu.internals import dtype as dtp
from pathway_tpu.internals.common import apply_with_type, coalesce, if_else
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.schema import column_definition
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.colnames import _MATCHED_ID, _SCORE
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    USearchKnn,
    USearchMetricKind,
)


def _coerce_doc_tuple(value: Any) -> tuple:
    """Normalize splitter/parser output entries to (text, metadata-dict)."""
    if isinstance(value, (tuple, list)):
        text = value[0]
        meta = value[1] if len(value) > 1 else {}
    else:
        text, meta = value, {}
    if isinstance(meta, Json):
        meta = meta.value
    return str(text), dict(meta or {})


class VectorStoreServer:
    def __init__(
        self,
        *docs: Table,
        embedder: Any,
        parser: Callable | None = None,
        splitter: Callable | None = None,
        doc_post_processors: Sequence[Callable] | None = None,
        index_params: dict | None = None,
    ):
        self.docs = list(docs)
        self.embedder = embedder
        self.parser = parser
        self.splitter = splitter
        self.doc_post_processors = list(doc_post_processors or [])
        try:
            self.embedding_dimension = embedder.get_embedding_dimension()
        except Exception:
            # detect dimensionality with one raw probe call, bypassing the
            # UDF cache (reference: vector_store.py:87 —
            # len(_coerce_sync(embedder.__wrapped__)("."))))
            try:
                from pathway_tpu.xpacks.llm._utils import (
                    _coerce_sync,
                    _unwrap_udf,
                )

                self.embedding_dimension = len(
                    _coerce_sync(_unwrap_udf(embedder))(".")
                )
            except Exception:
                self.embedding_dimension = None
        self._index_params = index_params or {}
        # Flight Recorder: document-pipeline + retrieval serving metrics
        # (REST transport latency is measured in io/http; these cover the
        # store-specific stages)
        from pathway_tpu.observability import REGISTRY

        self._m_chunks = REGISTRY.counter(
            "pathway_vector_store_chunks_total",
            "chunks produced by the split stage (pre-embedding)",
        )
        self._m_retrievals = REGISTRY.counter(
            "pathway_vector_store_retrievals_total",
            "retrieve queries formatted",
        )
        self._m_results = REGISTRY.histogram(
            "pathway_vector_store_result_docs",
            "documents returned per retrieve query",
            buckets=(0, 1, 2, 3, 5, 10, 20, 50, 100),
        )
        self._graph = self._build_graph()

    # --- document pipeline ---------------------------------------------------

    def _clean_tables(self, docs: Iterable[Table]) -> list[Table]:
        out = []
        for doc in docs:
            cols = doc.column_names()
            exprs: dict[str, Any] = {"data": doc[cols[0]] if "data" not in cols else doc.data}
            if "_metadata" in cols:
                exprs["_metadata"] = doc["_metadata"]
            else:
                exprs["_metadata"] = apply_with_type(
                    lambda *_: Json({}), Json, doc[cols[0]]
                )
            out.append(doc.select(**exprs))
        return out

    def _build_graph(self) -> dict:
        docs_tables = self._clean_tables(self.docs)
        if not docs_tables:
            raise ValueError("provide at least one document table")
        docs = docs_tables[0]
        if len(docs_tables) > 1:
            docs = docs.concat_reindex(*docs_tables[1:])

        parser = self.parser
        if parser is None:
            from pathway_tpu.xpacks.llm.parsers import Utf8Parser

            parser = Utf8Parser()

        def parse_doc(data: Any, metadata: Any) -> list:
            raw = parser.func(data) if hasattr(parser, "func") else parser(data)
            if isinstance(metadata, Json):
                base_meta = dict(metadata.value or {})
            else:
                base_meta = dict(metadata or {})
            out = []
            for entry in raw:
                text, meta = _coerce_doc_tuple(entry)
                out.append(Json({"text": text, "metadata": {**base_meta, **meta}}))
            return out

        parsed = docs.select(
            docs_list=apply_with_type(parse_doc, list, docs.data, docs._metadata)
        ).flatten(this.docs_list)
        parsed = parsed.select(data_json=this.docs_list)

        for processor in self.doc_post_processors:

            def post_proc(data_json: Json, _proc=processor) -> Json:
                d = data_json.value
                text, meta = _proc(d["text"], d["metadata"])
                return Json({"text": text, "metadata": meta})

            parsed = parsed.select(
                data_json=apply_with_type(post_proc, Json, this.data_json)
            )

        splitter = self.splitter
        if splitter is None:
            from pathway_tpu.xpacks.llm.splitters import NullSplitter

            splitter = NullSplitter()

        m_chunks = self._m_chunks
        from pathway_tpu.observability.tracing import get_tracer

        _tracer = get_tracer()

        def split_doc(data_json: Json) -> list:
            with _tracer.span("vector_store.chunk") as sp:
                d = data_json.value
                fn = splitter.func if hasattr(splitter, "func") else splitter
                chunks = fn(d["text"])
                out = []
                for entry in chunks:
                    text, meta = _coerce_doc_tuple(entry)
                    out.append(
                        Json(
                            {
                                "text": text,
                                "metadata": {**d["metadata"], **meta},
                            }
                        )
                    )
                sp.set_attribute("chunks", len(out))
            m_chunks.inc(len(out))
            return out

        chunked = parsed.select(
            chunks=apply_with_type(split_doc, list, this.data_json)
        ).flatten(this.chunks)
        chunked_docs = chunked.select(
            text=apply_with_type(lambda j: j.value["text"], str, this.chunks),
            metadata=apply_with_type(
                lambda j: Json(j.value["metadata"]), Json, this.chunks
            ),
        )
        chunked_docs = chunked_docs.filter(chunked_docs.text.str.len() > 0)

        embedded = chunked_docs.with_columns(
            embedding=self.embedder(chunked_docs.text)
        )

        inner = USearchKnn(
            embedded.embedding,
            embedded.metadata,
            dimensions=self.embedding_dimension,
            metric=USearchMetricKind.COS,
            **self._index_params,
        )
        index = DataIndex(embedded, inner)
        return {
            "docs": docs,
            "chunked_docs": chunked_docs,
            "embedded": embedded,
            "index": index,
        }

    @property
    def index(self) -> DataIndex:
        return self._graph["index"]

    # --- query schemas (reference: vector_store.py:311-437) ------------------

    class StatisticsQuerySchema(pw.Schema):
        pass

    class QueryResultSchema(pw.Schema):
        result: Json

    class InputResultSchema(pw.Schema):
        result: Json

    class FilterSchema(pw.Schema):
        metadata_filter: str | None = column_definition(
            default_value=None, dtype=str
        )
        filepath_globpattern: str | None = column_definition(
            default_value=None, dtype=str
        )

    InputsQuerySchema = FilterSchema

    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int = column_definition(default_value=3, dtype=int)
        metadata_filter: str | None = column_definition(
            default_value=None, dtype=str
        )
        filepath_globpattern: str | None = column_definition(
            default_value=None, dtype=str
        )

    # --- queries -------------------------------------------------------------

    @staticmethod
    def merge_filters(queries: Table) -> Table:
        """Combine metadata_filter + filepath_globpattern into one filter
        expression (reference: vector_store.py:359)."""

        def combine(metadata_filter, globpattern) -> str | None:
            parts = []
            if metadata_filter:
                if "`" in metadata_filter or '"' in metadata_filter:
                    # normalize jmespath-style quoting BEFORE parsing, as
                    # the reference does (document_store.py:345): backtick
                    # literals become single-quoted, stray double quotes
                    # are dropped; plain single-quoted filters pass through
                    metadata_filter = (
                        metadata_filter.replace("'", r"\'")
                        .replace("`", "'")
                        .replace('"', "")
                    )
                parts.append(f"({metadata_filter})")
            if globpattern:
                parts.append(f"globmatch('{globpattern}', path)")
            return " && ".join(parts) if parts else None

        queries = queries.with_columns(
            metadata_filter=apply_with_type(
                combine,
                dtp.Optional_(dtp.STR),
                this.metadata_filter,
                this.filepath_globpattern,
            )
        )
        return queries.without("filepath_globpattern")

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        queries = self.merge_filters(retrieval_queries)
        emb = self.embedder(queries.query)
        queries = queries.with_columns(_q_emb=emb)
        jr = self.index.query_as_of_now(
            queries._q_emb,
            number_of_matches=queries.k,
            metadata_filter=queries.metadata_filter,
        )
        from pathway_tpu.internals.thisclass import right

        raw = jr.select(
            texts=right["text"],
            metas=right["metadata"],
            scores=right[_SCORE],
        )

        m_retrievals, m_results = self._m_retrievals, self._m_results
        from pathway_tpu.observability.tracing import get_tracer

        _tracer = get_tracer()

        def fmt(texts, metas, scores) -> Json:
            # Trace Weaver: retrieval formatting span — the last store
            # stage a request crosses before the REST response writer
            with _tracer.span("vector_store.retrieve") as sp:
                out = []
                if texts is not None:
                    for t, m, s in zip(texts, metas, scores):
                        out.append(
                            {
                                "text": t,
                                "metadata": (
                                    m.value if isinstance(m, Json) else m
                                ),
                                # scores are negative distances (cos - 1)
                                "dist": -float(s),
                            }
                        )
                sp.set_attribute("results", len(out))
            m_retrievals.inc()
            m_results.observe(len(out), exemplar=sp.trace_id)
            return Json(out)

        return raw.select(
            result=apply_with_type(
                fmt, Json, raw.texts, raw.metas, raw.scores
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        stats = self._graph["chunked_docs"].reduce(
            count=reducers.count(),
        )

        def fmt(count) -> Json:
            return Json(
                {
                    "file_count": int(count) if count is not None else 0,
                    "last_modified": None,
                    "last_indexed": None,
                }
            )

        # every query joins the single global-stats row (constant join key)
        from pathway_tpu.internals.thisclass import right

        joined = info_queries.join_left(
            stats.with_columns(_one=1),
            if_else(info_queries.id == info_queries.id, 1, 1)
            == right["_one"],
            id=info_queries.id,
        )
        return joined.select(
            result=apply_with_type(fmt, Json, right["count"])
        )

    def inputs_query(self, input_queries: Table) -> Table:
        metas = self._graph["chunked_docs"].reduce(
            metas=reducers.tuple(this.metadata)
        )
        queries = self.merge_filters(input_queries)
        from pathway_tpu.internals.thisclass import right
        from pathway_tpu.stdlib.indexing._filters import compile_filter

        joined = queries.join_left(
            metas.with_columns(_one=1),
            if_else(queries.id == queries.id, 1, 1) == right["_one"],
            id=queries.id,
        )

        def fmt(metas_v, flt) -> Json:
            pred = compile_filter(flt) if flt else None
            seen = []
            out = []
            for m in metas_v or ():
                mv = m.value if isinstance(m, Json) else m
                if pred is not None and not pred(mv):
                    continue
                key = mv.get("path") if isinstance(mv, dict) else str(mv)
                if key in seen:
                    continue
                seen.append(key)
                out.append(mv)
            return Json(out)

        return joined.select(
            result=apply_with_type(
                fmt, Json, right["metas"], queries.metadata_filter
            )
        )

    # --- REST serving (reference: vector_store.py:478) ------------------------

    def run_server(
        self,
        host: str,
        port: int,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        qos: Any = None,
        **kwargs,
    ):
        from pathway_tpu.io.http import PathwayWebserver, rest_connector

        webserver = PathwayWebserver(host=host, port=port)
        self._webserver = webserver

        def serve(route, schema, handler):
            queries, writer = rest_connector(
                webserver=webserver,
                route=route,
                schema=schema,
                methods=("GET", "POST"),
                delete_completed_queries=True,
                qos=qos,
            )
            result = handler(queries)
            writer(result.select(query_id=result.id, result=result.result))

        serve("/v1/retrieve", self.RetrieveQuerySchema, self.retrieve_query)
        serve("/v1/statistics", self.StatisticsQuerySchema, self.statistics_query)
        serve("/v1/inputs", self.InputsQuerySchema, self.inputs_query)

        def run():
            pw.run(terminate_on_error=terminate_on_error)

        if threaded:
            t = threading.Thread(target=run, daemon=True, name="VectorStoreServer")
            t.start()
            return t
        run()

    def drain(self, grace_s: float | None = None) -> bool:
        """Graceful shutdown of a running ``run_server``: stop admitting,
        flush in-flight micro-batches, answer every admitted query, then
        close the webserver (requires ``qos=`` to have enabled the gate;
        ungated servers just stop the listener)."""
        ws = getattr(self, "_webserver", None)
        if ws is None:
            return True
        return ws.drain(grace_s)

    def __repr__(self):
        return f"VectorStoreServer({self.embedder!r})"


class VectorStoreClient:
    """HTTP client for VectorStoreServer (reference: vector_store.py:651)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int = 15,
        additional_headers: dict | None = None,
    ):
        if url is None:
            url = f"http://{host}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.headers = additional_headers or {}

    def query(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        import requests

        resp = requests.post(
            f"{self.url}/v1/retrieve",
            json={
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
            headers=self.headers,
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        import requests

        resp = requests.post(
            f"{self.url}/v1/statistics",
            json={},
            headers=self.headers,
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()

    def get_input_files(
        self,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list:
        import requests

        resp = requests.post(
            f"{self.url}/v1/inputs",
            json={
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
            headers=self.headers,
            timeout=self.timeout,
        )
        resp.raise_for_status()
        return resp.json()
