"""Token Loom — the on-device generation stage of the serving plane.

Closes the RAG loop the xpack serves: ask -> retrieve (the existing KNN
read plane) -> generate (a continuous-batching decode scheduler over a
paged, arrangement-backed KV cache).  See:

* :mod:`pathway_tpu.generate.kv_cache` — fixed-size KV pages in a block
  pool with per-sequence page tables, mirrored into arrangement ledgers
  (the PR-7 substrate) so generation state snapshots incrementally and
  survives kill/restart;
* :mod:`pathway_tpu.generate.scheduler` — decode steps admitted through
  the Surge-Gate EDF micro-batcher on the power-of-two pad ladder, new
  sequences joining between steps, deadline propagation dropping
  expired generations MID-decode (504, pages reclaimed);
* :mod:`pathway_tpu.generate.serving` — the ``/generate`` route:
  retrieve -> prompt assembly -> streamed decode, behind the same
  router/staleness/tenant machinery as every other read.
"""

from pathway_tpu.generate.kv_cache import KvLedger, PagePool
from pathway_tpu.generate.scheduler import (
    DecodeScheduler,
    GenerateConfig,
    GenerationRequest,
)
from pathway_tpu.generate.serving import attach_generate

__all__ = [
    "KvLedger",
    "PagePool",
    "DecodeScheduler",
    "GenerateConfig",
    "GenerationRequest",
    "attach_generate",
]
