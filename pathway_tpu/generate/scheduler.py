"""Continuous-batching decode scheduler on Surge Gate.

One scheduler per generation replica.  Requests are admitted through
the existing EDF :class:`~pathway_tpu.serving.batcher.MicroBatcher`
(same deadline-at-flush semantics: an expired request is 504'd without
ever touching the device), join the active set BETWEEN decode steps,
and from then on every step advances every active sequence by one
token on the power-of-two pad ladder — batch x padded-seq shapes land
on buckets the jitted ``decode_step`` already compiled (the Tick Forge
compile-cache argument applied to generation).

Prefill IS decode here: a joining sequence's prompt tokens are fed one
per step through the same jitted function (logits ignored until the
prompt is consumed), so there is exactly one code path and a restored
run provably continues the same computation.  ``generate.prefill``
spans cover admission -> first sampled token; ``generate.decode_step``
spans cover each engine step.

Deadline propagation drops expired generations MID-decode: before
every step the scheduler sweeps the active set, answers 504, reclaims
the sequence's pages into the pool and retracts its ledger rows —
never another step for a dead deadline
(``pathway_generate_dropped_mid_decode_total``).

Durability: every ``snapshot_every`` steps the scheduler mirrors pages
that changed since the last mirror (pages fully written earlier are
immutable — bytes written scale with churn, the State Ledger
argument) plus per-sequence resume metadata into the
:class:`~pathway_tpu.generate.kv_cache.KvLedger`, then writes the
incremental segment snapshot.  ``restore=`` rebuilds pools, page
tables and sequence state from the newest manifest; decoding continues
where the snapshot left off and — greedy or seeded sampling being
deterministic — reproduces the uninterrupted run's tokens exactly.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from pathway_tpu.generate.kv_cache import KvLedger, PagePool
from pathway_tpu.serving.admission import DeadlineExceeded, ShedError
from pathway_tpu.serving.batcher import MicroBatcher
from pathway_tpu.serving.config import QoSConfig

_ENV_PREFIX = "PATHWAY_GENERATE_"
# the page-pool default; the Graph Doctor's generation-serving rule
# flags a plane running on it (INFO) — an explicit size is the memory
# budget statement
DEFAULT_PAGES = 64


def generate_enabled_via_env() -> bool:
    """``PATHWAY_GENERATE=1`` arms the generation stage on a replica
    (serving/replica.py main) — off keeps the read plane byte-identical
    to the pre-generation topology."""
    return os.environ.get("PATHWAY_GENERATE", "0").lower() in (
        "1",
        "true",
        "yes",
    )


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(_ENV_PREFIX + name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{_ENV_PREFIX}{name}={raw!r} is not an int"
        ) from None


@dataclass(frozen=True)
class GenerateConfig:
    """Generation-stage policy: decoder shape + page pool + scheduler
    knobs.  Every knob has a ``PATHWAY_GENERATE_*`` override."""

    n_pages: int = DEFAULT_PAGES
    page_size: int = 16
    max_batch: int = 8
    max_new_tokens: int = 32  # default per request (body may lower it)
    max_len: int = 256  # hard per-sequence token bound (pages permitting)
    snapshot_every: int = 0  # decode steps between snapshots; 0 = off
    store_root: str | None = None
    kernel: str = "auto"  # auto | ref | pallas
    decoder_seed: int = 0
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    head_dim: int = 32
    ffn_dim: int = 256

    @classmethod
    def from_env(cls) -> "GenerateConfig":
        kernel = os.environ.get(_ENV_PREFIX + "KERNEL", "") or "auto"
        if kernel not in ("auto", "ref", "pallas"):
            raise ValueError(
                f"{_ENV_PREFIX}KERNEL={kernel!r} must be auto|ref|pallas"
            )
        return cls(
            n_pages=_env_int("PAGES", DEFAULT_PAGES),
            page_size=_env_int("PAGE_SIZE", 16),
            max_batch=_env_int("MAX_BATCH", 8),
            max_new_tokens=_env_int("MAX_TOKENS", 32),
            max_len=_env_int("MAX_LEN", 256),
            snapshot_every=_env_int("SNAPSHOT_EVERY", 0),
            store_root=os.environ.get(_ENV_PREFIX + "STORE") or None,
            kernel=kernel,
            decoder_seed=_env_int("SEED", 0),
        )

    def decoder_config(self):
        from pathway_tpu.xpacks.llm.decoder import DecoderConfig

        return DecoderConfig(
            dim=self.dim,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            head_dim=self.head_dim,
            ffn_dim=self.ffn_dim,
            max_len=self.max_len,
            page_size=self.page_size,
        )


class GenerationRequest:
    """One admitted-or-not generation crossing the scheduler.  Exposes
    ``deadline`` for the micro-batcher's EDF heap and a ``wait()`` the
    serving handler blocks on (in an executor)."""

    def __init__(
        self,
        request_id: str,
        prompt_tokens: list[int],
        *,
        deadline: float,
        max_new_tokens: int,
        tenant: str | None = None,
        tenant_class: str | None = None,
        temperature: float = 0.0,
        top_k: int = 40,
        seed: int = 0,
        on_token: Callable[[int, bool], None] | None = None,
        traceparent: str | None = None,
    ):
        self.request_id = request_id
        self.prompt_tokens = list(prompt_tokens)
        self.deadline = float(deadline)
        self.order = self.deadline  # MicroBatcher heap key (plain EDF)
        self.max_new_tokens = int(max_new_tokens)
        self.tenant = tenant
        self.tenant_class = tenant_class
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.on_token = on_token
        self.traceparent = traceparent
        self.created_at = time.monotonic()
        self.done = threading.Event()
        self.result: dict | None = None
        # optional completion hook (the serving handler parks an
        # asyncio.Event behind it so no executor thread blocks per
        # in-flight generation); called AFTER result/done are set
        self.on_done: Callable[[], None] | None = None

    def finish(self, result: dict) -> None:
        self.result = result
        self.done.set()
        if self.on_done is not None:
            try:
                self.on_done()
            except Exception:
                pass

    def wait(self, timeout: float | None = None) -> dict | None:
        self.done.wait(timeout)
        return self.result


@dataclass
class _Seq:
    """One in-flight sequence: request plumbing + decode cursor."""

    seq_id: int
    req: GenerationRequest | None
    tokens: list[int]  # prompt + generated so far
    prompt_len: int
    max_new: int
    temperature: float
    top_k: int
    seed: int
    pages: list[int] = field(default_factory=list)
    n_fed: int = 0  # tokens written into the KV cache
    n_mirrored: int = 0  # tokens covered by the ledger mirror
    generated: list[int] = field(default_factory=list)
    trace_ctx: Any = None  # parsed parent SpanContext (or None)
    first_token_at: float | None = None
    deadline: float = 0.0
    tenant: str | None = None

    @property
    def next_token(self) -> int:
        return self.tokens[self.n_fed]

    @property
    def target_len(self) -> int:
        return self.prompt_len + self.max_new

    def meta(self, now: float) -> dict:
        """Resumable snapshot metadata (deadlines persist as REMAINING
        budget — monotonic clocks do not survive a process)."""
        return {
            "seq_id": self.seq_id,
            "tokens": list(self.tokens),
            "prompt_len": self.prompt_len,
            "max_new": self.max_new,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "seed": self.seed,
            "n_fed": self.n_fed,
            "n_generated": len(self.generated),
            "remaining_ms": max((self.deadline - now) * 1000.0, 0.0),
            "tenant": self.tenant,
            "n_pages": len(self.pages),
        }


_M: dict | None = None


def _metrics() -> dict:
    global _M
    if _M is None:
        from pathway_tpu.observability import REGISTRY

        _M = {
            "tokens": REGISTRY.counter(
                "pathway_generate_tokens_total",
                "tokens generated, by replica and kind (sampled = "
                "returned to a client; prefill = prompt tokens fed "
                "through the decode path)",
                labelnames=("replica", "kind"),
            ),
            "batch": REGISTRY.histogram(
                "pathway_generate_decode_batch_size",
                "live sequences per decode step (before pad-ladder "
                "padding)",
            ),
            "occupancy": REGISTRY.gauge(
                "pathway_generate_page_pool_occupancy",
                "fraction of the KV page pool in use, by replica",
                labelnames=("replica",),
            ),
            "dropped": REGISTRY.counter(
                "pathway_generate_dropped_mid_decode_total",
                "generations dropped MID-decode by deadline "
                "propagation (504, pages reclaimed), by replica",
                labelnames=("replica",),
            ),
            "requests": REGISTRY.counter(
                "pathway_generate_requests_total",
                "generation requests, by replica and outcome",
                labelnames=("replica", "outcome"),
            ),
            "ttft": REGISTRY.histogram(
                "pathway_generate_ttft_seconds",
                "admission -> first sampled token, by replica",
                labelnames=("replica",),
            ),
            "steps": REGISTRY.counter(
                "pathway_generate_decode_steps_total",
                "decode steps executed, by replica",
                labelnames=("replica",),
            ),
        }
    return _M


class DecodeScheduler:
    """Continuous-batching decode loop over the paged KV cache."""

    def __init__(
        self,
        config: GenerateConfig | None = None,
        *,
        qos: QoSConfig | None = None,
        replica_label: str = "0",
        restore: bool = True,
        ledger: Any = None,
    ):
        self.config = config or GenerateConfig.from_env()
        # PATHWAY_SERVING_* overrides apply (deadline budget/clamp,
        # queue bound, ...) — the generation-serving doctor rule clears
        # its deadline WARNING on those env vars, so they must actually
        # govern this plane
        self.qos = qos or QoSConfig.from_env(
            QoSConfig(
                max_batch_size=self.config.max_batch, max_wait_ms=2.0
            )
        )
        self.label = str(replica_label)
        self.dcfg = self.config.decoder_config()
        from pathway_tpu.xpacks.llm import decoder as dec

        self._dec = dec
        self.params = dec.init_params(
            self.dcfg, seed=self.config.decoder_seed
        )
        self.k_pool, self.v_pool = dec.empty_pools(
            self.dcfg, self.config.n_pages
        )
        self.pool = PagePool(self.config.n_pages)
        self.ledger = KvLedger()
        if self.config.kernel == "auto":
            import jax

            self.kernel = (
                "pallas" if jax.default_backend() == "tpu" else "ref"
            )
        else:
            self.kernel = self.config.kernel
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._active: list[_Seq] = []
        self._staged: list[GenerationRequest] = []
        self._waiting: list[GenerationRequest] = []
        self._seq_counter = 0
        self._step_count = 0
        self._n_params: int | None = None  # roofline: counted on demand
        self._stopping = False
        # out-of-thread snapshot(): executed AT the step boundary by
        # the decode thread (the pools are donated into the jitted
        # step — touching them mid-step from another thread races the
        # donation)
        self._snap_waiters: list = []
        self.finished: dict[str, dict] = {}  # request_id -> result (bounded)
        m = _metrics()
        self._m_tokens = m["tokens"]
        self._m_batch = m["batch"]
        self._m_dropped = m["dropped"].labels(self.label)
        self._m_requests = m["requests"]
        self._m_ttft = m["ttft"].labels(self.label)
        self._m_steps = m["steps"].labels(self.label)
        import weakref

        ref = weakref.ref(self)
        m["occupancy"].labels(self.label).set_function(
            lambda: (
                s.pool.occupancy() if (s := ref()) is not None else 0.0
            )
        )
        if restore and self.config.store_root:
            self._restore(self.config.store_root)
        # Tenant Weave past the admission gate (ROADMAP gen (f)): with
        # a tenant ledger attached, every submitted generation carries
        # the ledger's WFQ virtual-finish tag and the batcher's heap
        # orders by (vfinish, deadline) — a hot tenant's decode backlog
        # drains BEHIND the tail's fresh requests, extending weighted
        # fairness from admission into decode batching.  None keeps the
        # plain-EDF plane byte-identical.
        self.tenant_ledger = ledger
        # Tick Scope memory provider: the generate plane's resident
        # bytes — device KV page pools, the KvLedger arrangements, and
        # the host mirror — under owner "generate:<label>" (weakref: a
        # dead scheduler drops out of the snapshot at the next pull)
        from pathway_tpu.observability import tickscope as _ts

        def _generate_memory(r=ref):
            s = r()
            if s is None:
                return {}
            parts = dict(s.ledger.resident_bytes())
            parts["k_pool_device"] = int(s.k_pool.nbytes)
            parts["v_pool_device"] = int(s.v_pool.nbytes)
            return parts

        _ts.register_memory_provider(
            f"generate:{self.label}", _generate_memory
        )
        self.batcher = MicroBatcher(
            self.qos,
            dispatch=self._dispatch,
            reject=self._reject,
            capacity=self._slots_free,
            name=f"pw-generate-{self.label}",
            # requests carry their own heap key: plain EDF (deadline),
            # or the ledger-stamped (vfinish, deadline) WFQ tag
            order=lambda r: r.order,
        )
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"pw-decode-{self.label}"
        )
        self._thread.start()

    # --- admission --------------------------------------------------------

    def pages_needed(self, req: GenerationRequest) -> int:
        total = len(req.prompt_tokens) + req.max_new_tokens
        return -(-total // self.config.page_size)

    def submit(self, req: GenerationRequest) -> None:
        """Admit one generation request (raises ShedError when it can
        never be served)."""
        total = len(req.prompt_tokens) + req.max_new_tokens
        if total > self.config.max_len:
            raise ShedError(
                400,
                f"prompt+max_tokens ({total}) exceeds the decoder bound "
                f"({self.config.max_len})",
                0.0,
            )
        if self.pages_needed(req) > self.pool.capacity:
            raise ShedError(
                503,
                f"request needs {self.pages_needed(req)} KV pages; the "
                f"pool holds {self.pool.capacity} "
                "(raise PATHWAY_GENERATE_PAGES)",
                1.0,
            )
        with self._lock:
            if self._stopping:
                raise ShedError(503, "generation scheduler stopped", 1.0)
            backlog = len(self._waiting) + len(self._staged)
        # the EDF heap is part of the backlog: with the active set full
        # the batcher never dispatches, so without this term the queue
        # bound could never fire and a burst would grow the heap (and
        # its per-request waiters) until every entry 504'd at flush
        backlog += len(self.batcher)
        ledger = self.tenant_ledger
        tag = None
        if ledger is not None:
            # may shed 429 tenant_rate: fairness holds at the decode
            # door too, not just the HTTP admission gate
            tag = ledger.admit(req.tenant, req.tenant_class)
            req.order = (tag, req.deadline)
        if backlog >= self.qos.max_queue:
            if ledger is not None:
                # never entered the queue: give the fair-share token
                # (and, when possible, the WFQ clock advance) back
                ledger.refund(req.tenant, req.tenant_class, tag)
            self._m_requests.labels(self.label, "shed_queue").inc()
            raise ShedError(
                429, "generation queue full", 0.5
            )
        self.batcher.put(req)
        if ledger is not None:
            ledger.commit(req.tenant)

    def _slots_free(self) -> int:
        # dispatch capacity for the batcher: free active-set slots
        with self._lock:
            return max(
                self.config.max_batch
                - len(self._active)
                - len(self._staged)
                - len(self._waiting),
                0,
            )

    def _dispatch(self, reqs: list) -> None:
        # batcher flush thread: sequences JOIN BETWEEN steps — stage
        # them and let the decode loop fold them in at its boundary
        if self.tenant_ledger is not None:
            for r in reqs:
                # advance WFQ virtual time at dispatch (same contract
                # as the gate): later arrivals floor here, so an idle
                # tenant cannot bank virtual credit
                self.tenant_ledger.note_dispatched(r.order)
        with self._lock:
            self._staged.extend(reqs)
            self._cond.notify()

    def _reject(self, req: Any, exc: BaseException) -> None:
        if isinstance(exc, DeadlineExceeded):
            self._m_requests.labels(self.label, "expired_queued").inc()
            req.finish(
                {
                    "status": 504,
                    "error": "deadline expired before decode started",
                }
            )
        else:
            self._m_requests.labels(self.label, "shed_queue").inc()
            status = getattr(exc, "status", 503)
            req.finish({"status": status, "error": str(exc) or "shed"})

    # --- the decode loop --------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._stopping
                    and not self._active
                    and not self._staged
                    and not self._waiting
                    and not self._snap_waiters
                ):
                    self._cond.wait(0.5)
                last_round = self._stopping and not self._active
            self._serve_snapshot_waiters()
            if last_round:
                return
            try:
                self._step()
            except Exception:
                import logging

                logging.getLogger("pathway_tpu").exception(
                    "generate: decode step failed; dropping the batch"
                )
                with self._lock:
                    doomed, self._active = self._active, []
                for s in doomed:
                    self._finish_seq(
                        s,
                        {
                            "status": 500,
                            "error": "decode step failed",
                        },
                        outcome="error",
                    )

    def _sweep_expired(self, now: float) -> None:
        """Deadline propagation MID-decode: expired actives answer 504
        and their pages return to the pool before any further step."""
        with self._lock:
            dead = [s for s in self._active if s.deadline < now]
            self._active = [s for s in self._active if s.deadline >= now]
            dead_wait = [r for r in self._waiting if r.deadline < now]
            self._waiting = [
                r for r in self._waiting if r.deadline >= now
            ]
        if dead:
            # Fleet Lens: a mid-decode deadline drop is an incident (a
            # client saw a 504 after tokens had already been minted) —
            # one journal event per sweep, not per sequence
            from pathway_tpu.observability.journal import (
                record as journal_record,
            )

            journal_record(
                "mid-decode-drop",
                f"{len(dead)} generation(s) dropped mid-decode by "
                "deadline propagation",
                replica=self.label,
                dropped=len(dead),
                tokens_lost=sum(len(s.generated) for s in dead),
            )
        for s in dead:
            self._m_dropped.inc()
            self._finish_seq(
                s,
                {
                    "status": 504,
                    "error": "deadline expired mid-decode",
                    "tokens": len(s.generated),
                },
                outcome="dropped_mid_decode",
            )
        for r in dead_wait:
            self._m_requests.labels(self.label, "expired_queued").inc()
            r.finish(
                {
                    "status": 504,
                    "error": "deadline expired waiting for KV pages",
                }
            )

    def _admit_staged(self, now: float) -> None:
        """Fold staged + page-starved requests into the active set (at
        the step boundary, never mid-step)."""
        with self._lock:
            incoming = self._waiting + self._staged
            self._waiting, self._staged = [], []
        for req in incoming:
            with self._lock:
                room = len(self._active) < self.config.max_batch
            pages = (
                self.pool.try_alloc(self.pages_needed(req))
                if room
                else None
            )
            if pages is None:
                with self._lock:
                    self._waiting.append(req)  # retried next boundary
                continue
            with self._lock:
                self._seq_counter += 1
                seq_id = self._seq_counter
            from pathway_tpu.observability import tracing

            seq = _Seq(
                seq_id=seq_id,
                req=req,
                tokens=list(req.prompt_tokens),
                prompt_len=len(req.prompt_tokens),
                max_new=req.max_new_tokens,
                temperature=req.temperature,
                top_k=req.top_k,
                seed=req.seed,
                pages=pages,
                trace_ctx=tracing.parse_traceparent(req.traceparent),
                deadline=req.deadline,
                tenant=req.tenant,
            )
            with self._lock:
                self._active.append(seq)

    def _page_table_rows(self, seqs: list[_Seq], bucket: int) -> np.ndarray:
        pt = np.zeros((bucket, self.dcfg.max_pages), np.int32)
        for i, s in enumerate(seqs):
            pt[i, : len(s.pages)] = s.pages
        return pt

    def _step(self) -> None:
        now = time.monotonic()
        self._sweep_expired(now)
        self._admit_staged(now)
        with self._lock:
            batch = list(self._active[: self.config.max_batch])
        if not batch:
            return
        import jax.numpy as jnp

        from pathway_tpu.observability import tracing

        bucket = self.qos.bucket_for(len(batch))
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        seq_lens = np.zeros(bucket, np.int32)
        for i, s in enumerate(batch):
            tokens[i] = s.next_token
            positions[i] = s.n_fed
            seq_lens[i] = s.n_fed + 1
        pt = self._page_table_rows(batch, bucket)
        span = tracing.get_tracer().span(
            "generate.decode_step",
            replica=self.label,
            batch=len(batch),
            bucket=bucket,
        )
        with span:
            _rt0 = time.perf_counter()
            logits, self.k_pool, self.v_pool = self._dec.decode_step(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                self.k_pool,
                self.v_pool,
                jnp.asarray(pt),
                jnp.asarray(seq_lens),
                cfg=self.dcfg,
                kernel=self.kernel,
            )
            host_logits = np.asarray(logits)
        # Tick Scope roofline, family "paged_attention": one decode step
        # at this bucket. Analytic FLOPs (2 * params * batch for the
        # matmuls + the attention read over the live context) — the
        # pallas kernel has no XLA cost model off-TPU, and lower().
        # compile() here would double every bucket's compile time.
        try:
            from pathway_tpu.observability import tickscope as _ts

            _rl = _ts.roofline()
            _key = f"decode_b{bucket}_{self.kernel}"
            if not _rl.known("paged_attention", _key):
                if self._n_params is None:
                    import jax as _jax

                    self._n_params = sum(
                        l.size
                        for l in _jax.tree_util.tree_leaves(self.params)
                    )
                ctx = int(seq_lens.sum())
                _rl.register(
                    "paged_attention",
                    _key,
                    2.0 * self._n_params * bucket
                    + 4.0
                    * self.dcfg.n_layers
                    * self.dcfg.n_heads
                    * self.dcfg.head_dim
                    * ctx,
                    source="analytic",
                )
            _rl.observe(
                "paged_attention", _key, time.perf_counter() - _rt0
            )
        except Exception:  # pragma: no cover - defensive
            pass
        self._m_batch.observe(len(batch))
        self._m_steps.inc()
        finished: list[tuple[_Seq, dict]] = []
        for i, s in enumerate(batch):
            s.n_fed += 1
            if s.n_fed < s.prompt_len:
                # still feeding the prompt — prefill work is visible in
                # the token accounting (it dominates TTFT cost)
                self._m_tokens.labels(self.label, "prefill").inc()
                continue
            tok = self._dec.sample_token(
                host_logits[i],
                temperature=s.temperature,
                top_k=s.top_k,
                seed=s.seed,
                step=len(s.generated),
            )
            if s.first_token_at is None:
                s.first_token_at = time.monotonic()
                ttft = s.first_token_at - (
                    s.req.created_at if s.req is not None else now
                )
                self._m_ttft.observe(ttft)
                # prefill completion marker: admission -> first sampled
                # token, parented into the request's trace (the span is
                # emitted AT completion so no context token outlives a
                # loop iteration)
                with tracing.get_tracer().span(
                    "generate.prefill",
                    parent=s.trace_ctx,
                    root=s.trace_ctx is None,
                    replica=self.label,
                    prompt_tokens=s.prompt_len,
                    ttft_ms=round(ttft * 1000.0, 3),
                ):
                    pass
            s.generated.append(tok)
            s.tokens.append(tok)
            self._m_tokens.labels(self.label, "sampled").inc()
            done = (
                tok == self._dec.EOS
                or len(s.generated) >= s.max_new
                or s.n_fed + 1 >= self.config.max_len
            )
            if s.req is not None and s.req.on_token is not None:
                try:
                    s.req.on_token(tok, done)
                except Exception:
                    pass
            if done:
                finished.append(
                    (
                        s,
                        {
                            "status": 200,
                            "tokens": list(s.generated),
                            "text": self._dec.decode_tokens(s.generated),
                            "token_count": len(s.generated),
                        },
                    )
                )
        with self._lock:
            self._step_count += 1
            step_n = self._step_count
            done_ids = {id(s) for s, _ in finished}
            self._active = [
                s for s in self._active if id(s) not in done_ids
            ]
        for s, result in finished:
            self._finish_seq(s, result, outcome="ok")
        if finished:
            self.batcher.notify()  # active-set slots freed
        if (
            self.config.snapshot_every > 0
            and self.config.store_root
            and step_n % self.config.snapshot_every == 0
        ):
            self.snapshot()
        from pathway_tpu.testing import faults

        plan = faults.active()
        if plan is not None:
            plan.on_decode_step(step_n)

    def _finish_seq(
        self, seq: _Seq, result: dict, *, outcome: str
    ) -> None:
        """Answer + reclaim: pages return to the pool and the ledger
        retracts the sequence's rows the moment it leaves the plane."""
        with self._lock:  # vs stop(): exactly one side frees
            pages, seq.pages = seq.pages, []
        if pages:
            self.pool.free(pages)
        self.ledger.drop_seq(seq.seq_id)
        self._m_requests.labels(self.label, outcome).inc()
        if seq.req is not None:
            seq.req.finish(result)
            rid = seq.req.request_id
        else:
            rid = f"restored-{seq.seq_id}"
        self.finished[rid] = result
        while len(self.finished) > 256:
            self.finished.pop(next(iter(self.finished)))

    # --- durability -------------------------------------------------------

    def _mirror(self) -> None:
        """Mirror pages that changed since the last mirror (earlier
        pages are immutable once full) + resume metadata into the
        ledger arrangements."""
        now = time.monotonic()
        p = self.config.page_size
        with self._lock:
            # pages captured under the SAME lock _finish_seq swaps them
            # under: an out-of-thread snapshot() racing a completion
            # must never index a reclaimed (possibly reallocated) page
            actives = [(s, list(s.pages)) for s in self._active]
        k_host = None
        v_host = None
        for s, pages in actives:
            if not pages:
                continue  # finished between capture and here
            first_dirty = s.n_mirrored // p
            last = max(s.n_fed - 1, 0) // p
            if s.n_fed > 0 and last < len(pages):
                if k_host is None:
                    # one bulk device->host pull per mirror pass
                    k_host = np.asarray(self.k_pool)
                    v_host = np.asarray(self.v_pool)
                for page_idx in range(first_dirty, last + 1):
                    pid = pages[page_idx]
                    self.ledger.put_page(
                        s.seq_id,
                        page_idx,
                        k_host[:, pid].copy(),
                        v_host[:, pid].copy(),
                    )
            s.n_mirrored = s.n_fed
            self.ledger.put_seq(s.seq_id, s.meta(now))

    def _snapshot_inline(self) -> dict | None:
        root = self.config.store_root
        if not root:
            return None
        self._mirror()
        return self.ledger.snapshot(root)

    def _serve_snapshot_waiters(self) -> None:
        with self._lock:
            waiters, self._snap_waiters = self._snap_waiters, []
        for holder, ev in waiters:
            try:
                holder["result"] = self._snapshot_inline()
            except Exception as exc:
                holder["error"] = exc
            ev.set()

    def snapshot(self, timeout: float = 30.0) -> dict | None:
        """Mirror + write the incremental arrangement snapshot.

        Safe from any thread: an out-of-thread call is executed AT the
        next step boundary by the decode thread (the jitted step
        donates the pools, so another thread must never read them
        mid-step); the decode thread's own periodic call runs inline."""
        if (
            threading.current_thread() is self._thread
            or not self._thread.is_alive()
        ):
            return self._snapshot_inline()
        holder: dict = {}
        ev = threading.Event()
        with self._cond:
            self._snap_waiters.append((holder, ev))
            self._cond.notify()
        if not ev.wait(timeout):
            raise TimeoutError(
                "decode loop did not reach a step boundary in time"
            )
        if "error" in holder:
            raise holder["error"]
        return holder.get("result")

    def _restore(self, root: str) -> None:
        led = KvLedger.restore(root)
        if led is None:
            return
        self.ledger = led
        now = time.monotonic()
        pages = led.live_pages()
        import jax.numpy as jnp

        k_pool = np.array(self.k_pool)  # writable host copies
        v_pool = np.array(self.v_pool)
        assigned: dict[tuple[int, int], int] = {}
        for (seq_id, page_idx), (k_page, v_page, _ident) in pages.items():
            got = self.pool.try_alloc(1)
            if got is None:  # pool shrank across the restart
                raise RuntimeError(
                    "KV page pool too small to restore the snapshot "
                    f"(needs > {self.pool.capacity} pages)"
                )
            pid = got[0]
            assigned[(seq_id, page_idx)] = pid
            k_pool[:, pid] = np.asarray(k_page, np.float32)
            v_pool[:, pid] = np.asarray(v_page, np.float32)
        self.k_pool = jnp.asarray(k_pool)
        self.v_pool = jnp.asarray(v_pool)
        for seq_id, meta in led.live_seqs().items():
            n_fed = int(meta["n_fed"])
            n_pages_owned = int(
                meta.get(
                    "n_pages",
                    -(-max(n_fed, 1) // self.config.page_size),
                )
            )
            page_ids: list[int] = []
            for page_idx in range(n_pages_owned):
                pid = assigned.get((seq_id, page_idx))
                if pid is None:
                    # a page the mirror had not covered yet (or a page
                    # reserved but never written): fresh allocation
                    got = self.pool.try_alloc(1)
                    if got is None:
                        raise RuntimeError(
                            "KV page pool too small to restore"
                        )
                    pid = got[0]
                page_ids.append(pid)
            gen_count = int(meta["n_generated"])
            toks = [int(t) for t in meta["tokens"]]
            seq = _Seq(
                seq_id=seq_id,
                req=None,  # the client died with the old process
                tokens=toks,
                prompt_len=int(meta["prompt_len"]),
                max_new=int(meta["max_new"]),
                temperature=float(meta["temperature"]),
                top_k=int(meta["top_k"]),
                seed=int(meta["seed"]),
                pages=page_ids,
                n_fed=n_fed,
                n_mirrored=n_fed,
                generated=toks[
                    len(toks) - gen_count:] if gen_count else [],
                deadline=now + float(meta["remaining_ms"]) / 1000.0,
                tenant=meta.get("tenant"),
            )
            self._seq_counter = max(self._seq_counter, seq_id)
            self._active.append(seq)
        self.restored_seqs = len(self._active)

    # --- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "active_seqs": len(self._active),
                "waiting": len(self._waiting) + len(self._staged),
                "decode_steps": self._step_count,
                "free_pages": self.pool.free_pages,
                "page_capacity": self.pool.capacity,
                "kernel": self.kernel,
            }

    def drain(self, timeout: float = 30.0) -> bool:
        """Finish everything admitted; returns False on timeout."""
        deadline = time.monotonic() + timeout
        self.batcher.drain()
        while time.monotonic() < deadline:
            with self._lock:
                idle = (
                    not self._active
                    and not self._staged
                    and not self._waiting
                )
            if idle and not len(self.batcher):
                return True
            time.sleep(0.02)
        return False

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            doomed = self._active + self._waiting + self._staged
            self._active, self._waiting, self._staged = [], [], []
            self._cond.notify()
        self.batcher.close(
            reject_queued=ShedError(
                503, "generation scheduler stopped", 1.0
            )
        )
        for item in doomed:
            req = item.req if isinstance(item, _Seq) else item
            if isinstance(item, _Seq):
                with self._lock:
                    pages, item.pages = item.pages, []
                if pages:
                    self.pool.free(pages)
            if req is not None:
                req.finish(
                    {"status": 503, "error": "scheduler stopped"}
                )
        self._thread.join(timeout=5.0)
