"""Paged KV cache as framework state.

The decode kernels read KV through a block pool + page tables
(ops/paged_attention.py); this module owns the OTHER half of the paged
cache story: allocation accounting and durability.

* :class:`PagePool` — host-side free-list over the physical pages of
  the device pools.  Page 0 is reserved as the null page (padded batch
  slots write there), so a pool of ``n_pages`` serves ``n_pages - 1``
  allocatable pages.

* :class:`KvLedger` — the generation state mirrored into arrangement
  ledgers (the PR-7 substrate), exactly the GroupBy-ledger pattern:
  every touched page is a retract+insert of one row, so the
  content-addressed segment snapshot writes only churned state, a
  kill/restart rebuilds the pools byte-identically from the newest
  manifest, and the rows could ride the same delta/replication
  machinery as any other arrangement-backed table.  Two arrangements,
  because their value columns want different encodings:

  - ``pages``: one row per (sequence, logical page) holding the page's
    K and V arrays ``[L, H, P, Dp]`` (uniform ndarrays -> the segment
    codec stacks them as raw buffers, mmap-recoverable) plus an int64
    identity column;
  - ``seqs``: one row per in-flight sequence holding its resumable
    metadata dict (tokens fed so far, prompt length, sampling params —
    irregular object column -> pickled per segment).

  ``snapshot(dir)`` is atomic (segment files first, manifest rename
  last) and incremental (a segment id already on disk is never
  rewritten; superseded segment files are GC'd only after the manifest
  commit).  ``restore(dir)`` mmap-loads the manifest's segments and
  yields the consolidated rows to rebuild pools and scheduler state.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Iterable

import numpy as np

from pathway_tpu.engine.arrangement import Arrangement
from pathway_tpu.persistence.segments import (
    load_arrangement,
    manifest_of,
    segment_to_bytes,
)

NULL_PAGE = 0

_MANIFEST = "manifest.json"
_SEG_DIR = "segs"


class PagePool:
    """Free-list accounting for the physical pages of the KV pools."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"page pool needs >= 2 pages (1 null + 1 usable), got "
                f"{n_pages}"
            )
        self.n_pages = int(n_pages)
        # a set: O(1) double-free membership check — a list scan made
        # bulk frees quadratic in the pool size on the decode thread
        self._free: set[int] = set(range(NULL_PAGE + 1, self.n_pages))
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self.n_pages - 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - self.free_pages

    def occupancy(self) -> float:
        return self.in_use / self.capacity

    def try_alloc(self, n: int) -> list[int] | None:
        """n physical page ids, or None when the pool cannot cover them
        (never a partial grant — the caller either joins the batch with
        a full table or stays queued)."""
        if n <= 0:
            return []
        with self._lock:
            if len(self._free) < n:
                return None
            got = [self._free.pop() for _ in range(n)]
        return got

    def free(self, pages: Iterable[int]) -> None:
        with self._lock:
            for p in pages:
                p = int(p)
                if p == NULL_PAGE:
                    raise ValueError("cannot free the null page")
                if not (0 < p < self.n_pages):
                    raise ValueError(f"page {p} outside the pool")
                if p in self._free:
                    raise ValueError(f"double free of page {p}")
                self._free.add(p)


def _row_key(*parts: Any) -> int:
    h = hashlib.blake2b(
        ":".join(str(p) for p in parts).encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "little")


def seq_jk(seq_id: int) -> int:
    """A sequence's ledger join key — the jk every row the sequence
    owns (pages + metadata) groups under, and therefore the ownership
    hash the elastic resharder routes the sequence by
    (elastic/kv.py ``seq_owner``): one agreed fact, like every other
    plane's jk."""
    return _row_key("s", seq_id)


class KvLedger:
    """Arrangement mirror of the in-flight generation state."""

    def __init__(self):
        # pages: cols = [k_page, v_page, ident(int64[2]: seq, page_idx)]
        self.pages = Arrangement(3)
        # seqs: cols = [meta dict]
        self.seqs = Arrangement(1)
        self._shadow_pages: dict[tuple[int, int], tuple] = {}
        self._shadow_seqs: dict[int, dict] = {}
        self._lock = threading.Lock()
        # segment files already present in the snapshot dir, keyed
        # (arrangement name, epoch, seg_id) — primed from the restored
        # manifest so a continued run never rewrites a persisted file
        self._written: set[tuple[str, str, int]] = set()

    # --- mirror writes ----------------------------------------------------

    def _append(
        self, arr: Arrangement, jk: int, key: int, diff: int, cols: list
    ) -> None:
        def obj_col(c: Any) -> np.ndarray:
            # np.array([ndarray], object) would EXPLODE the payload
            # into an object array of scalars — build-and-assign keeps
            # the array a single element
            col = np.empty(1, object)
            col[0] = c
            return col

        arr.append(
            np.array([jk], np.uint64),
            np.array([key], np.uint64),
            np.array([diff], np.int64),
            [obj_col(c) for c in cols],
        )

    def put_page(
        self,
        seq_id: int,
        page_idx: int,
        k_page: np.ndarray,
        v_page: np.ndarray,
    ) -> None:
        """Mirror one (sequence, logical page) worth of KV state:
        retract the previous version, insert the new one."""
        jk = np.uint64(_row_key("s", seq_id))
        key = np.uint64(_row_key("p", seq_id, page_idx))
        ident = np.array([seq_id, page_idx], np.int64)
        with self._lock:
            old = self._shadow_pages.get((seq_id, page_idx))
            if old is not None:
                self._append(self.pages, jk, key, -1, list(old))
            cols = (k_page, v_page, ident)
            self._append(self.pages, jk, key, +1, list(cols))
            self._shadow_pages[(seq_id, page_idx)] = cols

    def put_seq(self, seq_id: int, meta: dict) -> None:
        jk = np.uint64(_row_key("s", seq_id))
        key = np.uint64(_row_key("m", seq_id))
        with self._lock:
            old = self._shadow_seqs.get(seq_id)
            if old is not None:
                self._append(self.seqs, jk, key, -1, [old])
            self._append(self.seqs, jk, key, +1, [meta])
            self._shadow_seqs[seq_id] = meta

    def drop_seq(self, seq_id: int) -> None:
        """Retract everything a finished/dropped sequence owns — its
        pages leave the ledger the moment the pool reclaims them."""
        jk = np.uint64(_row_key("s", seq_id))
        with self._lock:
            meta = self._shadow_seqs.pop(seq_id, None)
            if meta is not None:
                self._append(
                    self.seqs, jk, np.uint64(_row_key("m", seq_id)), -1,
                    [meta],
                )
            doomed = [k for k in self._shadow_pages if k[0] == seq_id]
            for k in doomed:
                cols = self._shadow_pages.pop(k)
                self._append(
                    self.pages,
                    jk,
                    np.uint64(_row_key("p", k[0], k[1])),
                    -1,
                    list(cols),
                )

    def resident_bytes(self) -> dict[str, int]:
        """Memory-ledger parts for Tick Scope: the two arrangements
        (whose object columns hold the SAME ndarrays the shadow dict
        points at — the +1 entry shares storage with ``_shadow_pages``,
        only retract/insert churn adds copies) and the host mirror
        counted by payload bytes."""
        with self._lock:
            mirror = 0
            for k_page, v_page, ident in self._shadow_pages.values():
                mirror += (
                    int(k_page.nbytes) + int(v_page.nbytes)
                    + int(ident.nbytes)
                )
        return {
            "pages_arrangement": self.pages.resident_bytes(),
            "seqs_arrangement": self.seqs.resident_bytes(),
            "host_mirror": mirror,
        }

    def live_seqs(self) -> dict[int, dict]:
        with self._lock:
            return dict(self._shadow_seqs)

    def live_pages(self) -> dict[tuple[int, int], tuple]:
        with self._lock:
            return dict(self._shadow_pages)

    # --- snapshot / restore ----------------------------------------------

    @staticmethod
    def _seg_path(root: str, name: str, epoch: str, seg_id: int) -> str:
        return os.path.join(root, _SEG_DIR, f"{name}-{epoch}-{seg_id}.seg")

    def snapshot(self, root: str) -> dict:
        """Write an incremental snapshot under ``root``; returns
        ``{"bytes_written": ..., "segments_written": ...}``.  Crash-safe
        at every point: segment files land first (content-addressed by
        (epoch, seg_id) — ids already on disk are skipped), the
        manifest commits by atomic rename, and files the new manifest
        no longer references are unlinked only after the rename."""
        os.makedirs(os.path.join(root, _SEG_DIR), exist_ok=True)
        with self._lock:
            manifests = {
                "pages": manifest_of(self.pages),
                "seqs": manifest_of(self.seqs),
            }
            arrs = {"pages": self.pages, "seqs": self.seqs}
            written_bytes = 0
            written_segs = 0
            referenced: set[str] = set()
            for name, arr in arrs.items():
                for seg in arr.segments:
                    path = self._seg_path(root, name, arr.epoch, seg.seg_id)
                    referenced.add(os.path.basename(path))
                    tag = (name, arr.epoch, seg.seg_id)
                    if tag in self._written and os.path.exists(path):
                        continue
                    blob = segment_to_bytes(seg)
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(blob)
                    os.replace(tmp, path)
                    self._written.add(tag)
                    written_bytes += len(blob)
                    written_segs += 1
            doc = json.dumps({"v": 1, "arrangements": manifests})
            tmp = os.path.join(root, _MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                f.write(doc)
            os.replace(tmp, os.path.join(root, _MANIFEST))
            # GC: only after the manifest no longer names them
            seg_dir = os.path.join(root, _SEG_DIR)
            for fname in os.listdir(seg_dir):
                if fname.endswith(".seg") and fname not in referenced:
                    try:
                        os.unlink(os.path.join(seg_dir, fname))
                    except OSError:
                        pass
            self._written = {
                (n, a.epoch, s.seg_id)
                for n, a in arrs.items()
                for s in a.segments
            }
        return {
            "bytes_written": written_bytes,
            "segments_written": written_segs,
        }

    @classmethod
    def restore(cls, root: str) -> "KvLedger | None":
        """Rebuild the ledger (arrangements + shadow state) from the
        newest committed snapshot; None when no manifest exists."""
        mpath = os.path.join(root, _MANIFEST)
        if not os.path.exists(mpath):
            return None
        with open(mpath) as f:
            doc = json.load(f)
        led = cls()

        def fetch(name: str, epoch: str):
            def _fetch(seg_id: int):
                path = cls._seg_path(root, name, epoch, seg_id)
                if not os.path.exists(path):
                    return None
                import mmap

                with open(path, "rb") as f:
                    return mmap.mmap(
                        f.fileno(), 0, access=mmap.ACCESS_READ
                    )

            return _fetch

        for name in ("pages", "seqs"):
            man = doc["arrangements"][name]
            arr = load_arrangement(man, fetch(name, man["epoch"]))
            setattr(led, name, arr)
            led._written.update(
                (name, man["epoch"], int(d["id"]))
                for d in man["segments"]
            )
        rows = led.pages.entries()
        for i in range(len(rows)):
            if rows.count[i] <= 0:
                continue
            k_page = rows.cols[0][i]
            v_page = rows.cols[1][i]
            seq_id, page_idx = (int(x) for x in rows.cols[2][i])
            led._shadow_pages[(seq_id, page_idx)] = (
                np.array(k_page),
                np.array(v_page),
                np.array([seq_id, page_idx], np.int64),
            )
        rows = led.seqs.entries()
        for i in range(len(rows)):
            if rows.count[i] <= 0:
                continue
            meta = rows.cols[0][i]
            led._shadow_seqs[int(meta["seq_id"])] = dict(meta)
        return led
