"""The ``/generate`` serving route: ask -> retrieve -> generate.

``attach_generate(replica_server)`` mounts a generation stage on a read
replica: the handler embeds the prompt with the plane's deterministic
``text_vector`` embedder, retrieves top-k context from the replica's
(delta-stream-fresh) KNN index, assembles the grounded prompt, and
streams the decode through the replica's
:class:`~pathway_tpu.generate.scheduler.DecodeScheduler`.

Contract (the read plane's degrade headers hold through generation):

* request body: ``{"prompt": str, "k": int (retrieval fan-in, 0 = no
  retrieval), "max_tokens": int, "temperature": float, "top_k": int,
  "seed": int, "stream": bool}``;
* ``x-pathway-deadline-ms`` bounds the WHOLE generation: an expired
  deadline drops the sequence mid-decode (504 — pages reclaimed, never
  another step); ``x-pathway-max-staleness-ms`` sheds 503 when the
  retrieval corpus is staler than the bound (same rule as ``/query``);
* responses carry ``x-pathway-replica`` / ``x-pathway-applied-tick`` /
  ``x-pathway-staleness-seconds`` (the retrieval corpus freshness the
  generation was conditioned on) plus ``x-pathway-generate-tokens``;
* ``stream: true`` answers NDJSON over chunked encoding: a ``meta``
  line (retrieved context, freshness), one line per sampled token, and
  a final ``done`` line.  Non-streaming responses are a single JSON
  object — the shape the failover router proxies.

The router routes ``/generate`` through the SAME occupancy/staleness/
tenant machinery as every read, but always to ONE member (generation
is stateful on the member holding the KV pages — scatter-gather is a
retrieval concept), see serving/router.py ``is_generate_route``.
"""

from __future__ import annotations

import json
import time
from typing import Any

import numpy as np

GENERATE_ROUTE = "/generate"


def is_generate_route(path: str) -> bool:
    # segment-exact: "/v1/generate" and "/generate/" match, a route
    # that merely ENDS in the word (e.g. "/regenerate") must not — on
    # a sharded plane a false match would divert a scatter-gather read
    # to a single member's partial corpus
    return path.rstrip("/").endswith(GENERATE_ROUTE)


def attach_generate(
    server: Any,
    scheduler: Any = None,
    *,
    route: str = GENERATE_ROUTE,
) -> Any:
    """Mount the generation stage on a ReplicaServer BEFORE ``start()``.
    Builds the scheduler from env (``PATHWAY_GENERATE_*``) when none is
    given; returns it."""
    if scheduler is None:
        from pathway_tpu.generate.scheduler import (
            DecodeScheduler,
            GenerateConfig,
        )

        scheduler = DecodeScheduler(
            GenerateConfig.from_env(),
            replica_label=str(server.replica_id),
            # Tenant Weave: the replica's ledger (PATHWAY_TENANT_QOS=1)
            # extends WFQ fairness past the admission gate into decode
            # batching — the batcher orders by (vfinish, deadline)
            ledger=getattr(server, "tenant_ledger", None),
        )
    server.generate_scheduler = scheduler
    server.extra_post_routes[route] = _handle_generate
    return scheduler


def assemble_prompt(prompt: str, matches: list) -> str:
    """Grounded prompt assembly: retrieved doc keys/scores prefix the
    user ask.  (With the bundled random-init decoder the text is not
    semantically meaningful — what matters, and what the e2e test pins,
    is that the tokens fed to the decoder are CONDITIONED on the
    retrieved context: a corpus change changes the generation.)"""
    ctx = " ".join(f"[doc {int(k)}:{score:.3f}]" for k, score in matches)
    return f"context: {ctx}\nask: {prompt}\nanswer:" if ctx else (
        f"ask: {prompt}\nanswer:"
    )


async def _handle_generate(http: Any, request: Any):
    """aiohttp handler running inside _ReplicaHttp (its loop thread)."""
    import asyncio

    from aiohttp import web

    from pathway_tpu.generate.scheduler import GenerationRequest
    from pathway_tpu.observability import tracing
    from pathway_tpu.serving.admission import ShedError
    from pathway_tpu.serving.replica import text_vector

    srv = http.server
    sched = srv.generate_scheduler
    span = tracing.get_tracer().span(
        "generate.request",
        parent=tracing.parse_traceparent(
            request.headers.get("traceparent")
        ),
        root=True,
        ingress=True,
        replica=srv.replica_id,
    )
    with span:
        staleness = srv.staleness_seconds()
        stale = srv.is_stale()
        headers = {
            "x-pathway-replica": str(srv.replica_id),
            "x-pathway-applied-tick": str(srv.applied_tick),
            "x-pathway-staleness-seconds": (
                f"{staleness:.3f}" if staleness is not None else "unknown"
            ),
        }
        if stale:
            headers["x-pathway-stale"] = "true"
        if span.context is not None:
            headers["traceparent"] = span.context.traceparent()
        # the retrieval-freshness bound: generation grounded on a
        # corpus staler than the client accepts must shed, not guess —
        # the SAME predicate as the /query read path
        from pathway_tpu.serving.replica import staleness_bound_exceeded

        if staleness_bound_exceeded(
            staleness,
            stale,
            request.headers.get("x-pathway-max-staleness-ms"),
        ):
            span.set_attribute("status", 503)
            return web.json_response(
                {
                    "error": "retrieval corpus staler than "
                    "x-pathway-max-staleness-ms"
                },
                status=503,
                headers={"Retry-After": "1.0", **headers},
            )
        try:
            values = await request.json()
        except ValueError:
            values = {}
        if not isinstance(values, dict) or not str(
            values.get("prompt", "")
        ).strip():
            span.set_attribute("status", 400)
            return web.json_response(
                {"error": "body must be a JSON object with `prompt`"},
                status=400,
                headers=headers,
            )
        prompt = str(values["prompt"])
        try:
            k = int(values.get("k", 3))
            max_tokens = int(
                values.get("max_tokens", sched.config.max_new_tokens)
            )
            temperature = float(values.get("temperature", 0.0))
            top_k = int(values.get("top_k", 40))
            seed = int(values.get("seed", 0))
        except (TypeError, ValueError):
            span.set_attribute("status", 400)
            return web.json_response(
                {"error": "k/max_tokens/temperature/top_k/seed must be "
                 "numbers"},
                status=400,
                headers=headers,
            )
        max_tokens = max(1, max_tokens)
        # deadline propagation: the generation inherits the request's
        # remaining budget and is dropped MID-decode past it.  Non-
        # finite budgets fall back to the default — a NaN deadline
        # compares False against every sweep predicate, which would
        # park the sequence forever with its KV pages pinned
        import math

        try:
            budget_ms = float(
                request.headers.get("x-pathway-deadline-ms", "")
            )
        except ValueError:
            budget_ms = sched.qos.default_deadline_ms
        if not math.isfinite(budget_ms):
            budget_ms = sched.qos.default_deadline_ms
        budget_ms = min(budget_ms, sched.qos.max_deadline_ms)
        deadline = time.monotonic() + budget_ms / 1000.0
        # retrieve: the existing KNN read plane, same index the /query
        # route answers from.  The search runs in an executor — it
        # takes the replica's _index_lock, and blocking the only event
        # loop would stall /replica/health into a router ejection.
        loop = asyncio.get_running_loop()
        matches: list = []
        if k > 0:
            if values.get("vec") is not None:
                try:
                    vec = np.asarray(
                        values["vec"], dtype=np.float32
                    ).reshape(-1)
                except (TypeError, ValueError):
                    span.set_attribute("status", 400)
                    return web.json_response(
                        {"error": "`vec` must be a numeric array"},
                        status=400,
                        headers=headers,
                    )
            else:
                vec = text_vector(prompt, srv.dim)
            results = await loop.run_in_executor(
                None, srv.search, [(vec, k, None)]
            )
            matches = [
                [int(key), float(score)] for key, score in results[0]
            ]
        from pathway_tpu.xpacks.llm.decoder import encode_text

        full_prompt = assemble_prompt(prompt, matches)
        prompt_tokens = encode_text(full_prompt)
        # leave room for the generation inside the decoder bound
        limit = sched.config.max_len - max_tokens
        if limit < 2:
            span.set_attribute("status", 400)
            return web.json_response(
                {"error": "max_tokens leaves no room for the prompt"},
                status=400,
                headers=headers,
            )
        prompt_tokens = prompt_tokens[:limit]
        stream = bool(values.get("stream", False))
        token_q: asyncio.Queue | None = (
            asyncio.Queue() if stream else None
        )

        def on_token(tok: int, done: bool) -> None:
            if token_q is not None:
                loop.call_soon_threadsafe(token_q.put_nowait, (tok, done))

        req = GenerationRequest(
            request_id=f"g{srv.replica_id}-{id(request):x}-"
            f"{int(time.monotonic() * 1e6):x}",
            prompt_tokens=prompt_tokens,
            deadline=deadline,
            max_new_tokens=max_tokens,
            tenant=request.headers.get("x-pathway-tenant"),
            tenant_class=request.headers.get("x-pathway-tenant-class"),
            temperature=temperature,
            top_k=top_k,
            seed=seed,
            on_token=on_token if stream else None,
            traceparent=(
                span.context.traceparent()
                if span.context is not None
                else None
            ),
        )
        done_ev = asyncio.Event()
        req.on_done = lambda: loop.call_soon_threadsafe(done_ev.set)
        if req.done.is_set():
            done_ev.set()  # finished before the hook landed
        try:
            sched.submit(req)
        except ShedError as e:
            span.set_attribute("status", e.status)
            return web.json_response(
                {"error": f"generation shed: {e.reason}"},
                status=e.status,
                headers={
                    "Retry-After": f"{e.retry_after_s:.3f}",
                    **headers,
                },
            )
        if stream:
            return await _stream_response(
                request, req, token_q, matches, headers, span
            )
        budget = deadline - time.monotonic() + 5.0
        try:
            await asyncio.wait_for(done_ev.wait(), timeout=max(budget, 0.1))
        except asyncio.TimeoutError:
            pass
        result = req.result
        if result is None:
            result = {"status": 504, "error": "generation timed out"}
        status = int(result.get("status", 500))
        span.set_attribute("status", status)
        headers["x-pathway-generate-tokens"] = str(
            result.get("token_count", len(result.get("tokens", []) or []))
            if status == 200
            else result.get("tokens", 0)
        )
        body = (
            {
                "text": result.get("text", ""),
                "tokens": result.get("tokens", []),
                "token_count": result.get("token_count", 0),
                "retrieved": matches,
                "request_id": req.request_id,
            }
            if status == 200
            else {"error": result.get("error", "generation failed")}
        )
        if status in (429, 503, 504):
            headers.setdefault("Retry-After", "1.0")
        return web.json_response(body, status=status, headers=headers)


async def _stream_response(
    request: Any,
    req: Any,
    token_q: Any,
    matches: list,
    headers: dict,
    span: Any,
):
    """NDJSON chunked streaming: meta line, token lines, done line."""
    from aiohttp import web

    resp = web.StreamResponse(
        status=200,
        headers={"content-type": "application/x-ndjson", **headers},
    )
    await resp.prepare(request)

    async def line(obj: dict) -> None:
        await resp.write((json.dumps(obj) + "\n").encode())

    try:
        return await _stream_body(req, token_q, matches, span, resp, line)
    except (ConnectionResetError, OSError):
        # client disconnected mid-stream: once the response is
        # PREPARED no second response can go out — swallow the write
        # failure (the scheduler finishes the sequence regardless) and
        # hand the half-written response back as-is
        span.set_attribute("status", "client_disconnect")
        return resp


async def _stream_body(
    req: Any, token_q: Any, matches: list, span: Any, resp: Any, line: Any
):
    import asyncio

    from pathway_tpu.xpacks.llm.decoder import decode_tokens

    await line({"meta": {"retrieved": matches, "request_id": req.request_id}})
    n = 0
    finished = False
    while not finished:
        # the request's own deadline bounds the wait; the scheduler's
        # mid-decode drop resolves req.done so the loop always ends
        if req.done.is_set():
            # every on_token call_soon_threadsafe preceded finish() on
            # the scheduler thread: one yield lets those callbacks land
            # so no trailing token line is dropped, then drain
            await asyncio.sleep(0)
            await asyncio.sleep(0.02)
            while not token_q.empty():
                tok, _d = token_q.get_nowait()
                n += 1
                await line(
                    {
                        "token": int(tok),
                        "text_delta": decode_tokens([int(tok)]),
                    }
                )
            break
        try:
            tok, done = await asyncio.wait_for(token_q.get(), timeout=0.25)
        except asyncio.TimeoutError:
            continue
        n += 1
        await line(
            {"token": int(tok), "text_delta": decode_tokens([int(tok)])}
        )
        finished = done
    # on_token(done=True) fires BEFORE finish() on the scheduler
    # thread: give the result a moment to land before reading it
    for _ in range(200):
        if req.done.is_set():
            break
        await asyncio.sleep(0.01)
    result = req.result or {"status": 504, "error": "dropped"}
    status = int(result.get("status", 500))
    # the HTTP status is committed (200 at prepare), but the replica's
    # request accounting must see the generation's REAL outcome — a
    # mid-stream 504 drop counted as 200 would hide deadline pressure
    # from streaming clients entirely
    resp._pathway_status_override = status
    span.set_attribute("status", status)
    span.set_attribute("streamed_tokens", n)
    if status == 200:
        await line(
            {
                "done": True,
                "token_count": result.get("token_count", n),
                "text": result.get("text", ""),
            }
        )
    else:
        await line(
            {
                "done": True,
                "status": status,
                "error": result.get("error", "generation failed"),
            }
        )
    await resp.write_eof()
    return resp
