"""Tenant Weave — per-tenant fair admission for the serving plane.

`serve_chaos` models a million-tenant zipf population, but every Surge
Gate decision so far was tenant-blind: one hot tenant filling the
admission queue (or draining the endpoint token bucket) starves the
zipf tail, and the shed falls on whoever arrives next — usually a tail
tenant that sent one request all day.  This module makes tenant
identity a first-class admission input:

* **Identity** rides the ``x-pathway-tenant`` request header (any
  opaque string; absent = the anonymous ``""`` tenant).  An optional
  ``x-pathway-tenant-class`` header selects a *weight class* from
  ``PATHWAY_TENANT_WEIGHTS`` (``class:weight,class:weight,...``;
  unknown/absent classes fall back to ``default``, weight 1.0).

* **Per-tenant token buckets** clamp each tenant to its *weighted fair
  share* of the endpoint capacity — but only **under pressure** (the
  endpoint bucket is out of tokens or the queue is half full), so the
  scheme stays work-conserving: a lone hot tenant on an idle endpoint
  uses everything; the moment the tail shows up, the hot tenant is
  clamped to ``capacity * w_i / W_active`` and *its* requests shed
  (429 ``tenant_rate``), leaving global tokens for everyone else.
  ``W_active`` is the exponentially-decayed activity-weighted sum
  (time constant ``ACTIVE_TAU_S`` / ``PATHWAY_TENANT_ACTIVE_TAU_S``) —
  it tracks diurnal swings smoothly, with no hard cliff when a tenant
  crosses an idle boundary; per-tenant state is LRU-bounded
  (``PATHWAY_TENANT_STATE_CAP``) so a million-tenant population costs
  a bounded dict, not a leak.

* **Weighted-fair EDF ordering**: every admitted request carries a
  WFQ virtual-finish tag (``vfinish += 1/weight`` per request, floored
  at the ledger's virtual now), and the micro-batcher orders its heap
  by ``(vfinish, deadline)`` — a hot tenant's backlog drains *behind*
  the tail's fresh requests while same-share requests keep EDF order.

* **Shed charges the hot tenant, not the queue tail**: when the
  admission queue is full, the gate asks :meth:`TenantLedger.pick_victim`
  for the queued request of the most over-share tenant; if that tenant
  is hotter than the arrival, the *victim* is evicted with 429
  (``tenant_evict``) and the arrival admitted — the tail never pays
  for the noisy neighbor's backlog.

* **Bounded per-tenant metric cardinality**: :class:`TenantLabeler`
  gives the top-``PATHWAY_TENANT_METRIC_TOPN`` (default 32) tenants by
  traffic real metric labels and folds everyone else into
  ``tenant="__other__"`` — a 1M-tenant population must not explode the
  MetricsRegistry.  Label assignment is sticky (no series churn) and
  backed by a bounded space-saving counter, so it is approximate but
  O(topn) in memory.

Escape hatch is total: with ``PATHWAY_TENANT_QOS`` unset (or 0) no
ledger is built anywhere and every admission/batching path is the
pre-Tenant-Weave code byte for byte.

Fault Forge: the ``flood=tenant:T,rps:R[,ticks:N]`` directive charges
synthetic load to tenant T through :meth:`TenantLedger.admit`'s
deterministic admission counter (see testing/faults.py), so fairness
tests need no wall-clock load generators.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any

TENANT_HEADER = "x-pathway-tenant"
TENANT_CLASS_HEADER = "x-pathway-tenant-class"
OTHER_LABEL = "__other__"

# time constant of the exponentially-decayed per-tenant activity that
# forms the fair-share denominator: a tenant's weight contribution is
# ``w * exp(-idle/τ)`` — full while it keeps sending, smoothly fading
# as it goes quiet.  This replaced the fixed 10 s ACTIVE window, whose
# hard expiry made every other tenant's fair share JUMP the instant a
# neighbor crossed the boundary (the diurnal-swing cliff: shares
# doubled at window expiry, then halved when the tenant returned).
# Override with PATHWAY_TENANT_ACTIVE_TAU_S.
ACTIVE_TAU_S = 10.0
# deprecated alias (pre-decay name); the semantics are now a time
# constant, not a cutoff
ACTIVE_WINDOW_S = ACTIVE_TAU_S
_ACTIVE_TAU_ENV = "PATHWAY_TENANT_ACTIVE_TAU_S"


def active_tau_s() -> float:
    raw = os.environ.get(_ACTIVE_TAU_ENV, "")
    if not raw:
        return ACTIVE_TAU_S
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"{_ACTIVE_TAU_ENV}={raw!r} is not a number"
        ) from None
    if not v > 0.0:
        raise ValueError(f"{_ACTIVE_TAU_ENV} must be > 0")
    return v

_ENABLED_ENV = "PATHWAY_TENANT_QOS"
_WEIGHTS_ENV = "PATHWAY_TENANT_WEIGHTS"
_TOPN_ENV = "PATHWAY_TENANT_METRIC_TOPN"
_STATE_CAP_ENV = "PATHWAY_TENANT_STATE_CAP"
_BURST_ENV = "PATHWAY_TENANT_BURST"
_RPS_ENV = "PATHWAY_TENANT_RPS"


def tenancy_enabled_via_env() -> bool:
    """``PATHWAY_TENANT_QOS=1`` arms per-tenant fair admission on every
    Surge Gate / replica admission controller.  Off (the default) keeps
    every serving path byte-identical to the tenant-blind plane."""
    return os.environ.get(_ENABLED_ENV, "0").lower() in ("1", "true", "yes")


def parse_weight_classes(raw: str | None = None) -> dict[str, float]:
    """``PATHWAY_TENANT_WEIGHTS``: ``class:weight,class:weight,...``
    (e.g. ``premium:4,default:1,batch:0.25``).  Weights must be > 0; a
    ``default`` class (weight 1.0) is added when absent — it is what
    unknown/unlabeled tenants resolve to."""
    if raw is None:
        raw = os.environ.get(_WEIGHTS_ENV, "")
    weights: dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.rpartition(":")
        if not sep or not name.strip():
            raise ValueError(
                f"{_WEIGHTS_ENV}: bad entry {part!r} (expected "
                "class:weight)"
            )
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"{_WEIGHTS_ENV}: weight {w!r} for class {name!r} is "
                "not a number"
            ) from None
        if not weight > 0.0:
            raise ValueError(
                f"{_WEIGHTS_ENV}: weight for class {name!r} must be > 0"
            )
        weights[name.strip()] = weight
    weights.setdefault("default", 1.0)
    return weights


def _env_int(name: str, default: int, floor: int = 1) -> int:
    raw = os.environ.get(name, "") or str(default)
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an int") from None
    return max(v, floor)


class TenancyConfig:
    """Parsed tenancy policy (one per process is fine — gates share)."""

    def __init__(
        self,
        weights: dict[str, float] | None = None,
        metric_topn: int | None = None,
        state_cap: int | None = None,
        burst: float | None = None,
        tenant_rps: float | None = None,
    ):
        self.weights = (
            dict(weights) if weights is not None else parse_weight_classes()
        )
        self.weights.setdefault("default", 1.0)
        self.metric_topn = (
            _env_int(_TOPN_ENV, 32) if metric_topn is None else int(metric_topn)
        )
        self.state_cap = (
            _env_int(_STATE_CAP_ENV, 65536)
            if state_cap is None
            else max(int(state_cap), 8)
        )
        if burst is None:
            raw = os.environ.get(_BURST_ENV, "") or "4"
            try:
                burst = float(raw)
            except ValueError:
                raise ValueError(
                    f"{_BURST_ENV}={raw!r} is not a number"
                ) from None
        self.burst = max(float(burst), 1.0)
        if tenant_rps is None:
            raw = os.environ.get(_RPS_ENV, "")
            tenant_rps = float(raw) if raw else None
        self.tenant_rps = tenant_rps

    def weight_of(self, tenant_class: str | None) -> float:
        if tenant_class is None:
            return self.weights["default"]
        return self.weights.get(tenant_class, self.weights["default"])


class TenantLabeler:
    """Bounded-cardinality tenant → metric-label mapping.

    The top-N tenants by (approximate, space-saving-counted) traffic
    earn real labels; everyone else folds into ``__other__``.  Labels
    are STICKY once assigned — at most ``topn`` real label series ever
    exist per family, and a demotion never orphans a series mid-scrape.
    Approximation bias matches the workload: under zipf skew the heavy
    hitters dominate the early counts and claim the slots."""

    def __init__(self, topn: int):
        self.topn = max(int(topn), 1)
        self._cap = 8 * self.topn  # space-saving summary bound
        self._counts: dict[str, int] = {}
        self._labeled: set[str] = set()
        self._lock = threading.Lock()

    def label(self, tenant: str) -> str:
        with self._lock:
            if tenant in self._labeled:
                self._counts[tenant] = self._counts.get(tenant, 0) + 1
                return tenant
            c = self._counts.get(tenant)
            if c is None:
                if len(self._counts) >= self._cap:
                    # space-saving: inherit (and evict) the current
                    # minimum so a late-arriving heavy hitter can still
                    # climb — labeled tenants are never evicted
                    victim = min(
                        (
                            t
                            for t in self._counts
                            if t not in self._labeled
                        ),
                        key=self._counts.__getitem__,
                        default=None,
                    )
                    if victim is None:
                        return OTHER_LABEL
                    c = self._counts.pop(victim)
                else:
                    c = 0
            self._counts[tenant] = c + 1
            if len(self._labeled) < self.topn:
                self._labeled.add(tenant)
                return tenant
            return OTHER_LABEL

    def peek(self, tenant: str) -> str:
        """The label this tenant currently resolves to, WITHOUT counting
        traffic (commit-time metric emission must not double the
        space-saving counts the admission path already charged)."""
        with self._lock:
            return tenant if tenant in self._labeled else OTHER_LABEL

    def labeled(self) -> set[str]:
        with self._lock:
            return set(self._labeled)


class _TenantState:
    __slots__ = ("tokens", "last_refill", "vfinish", "last_seen", "weight")

    def __init__(self, now: float, weight: float, burst: float):
        self.tokens = burst
        self.last_refill = now
        self.vfinish = 0.0
        self.last_seen = now
        self.weight = weight


class TenantLedger:
    """Per-tenant fair-admission state for ONE route (gate or replica).

    ``capacity_rps`` is the endpoint's capacity envelope (usually the
    gate's ``rate_limit_rps``); per-tenant fair share is
    ``capacity * w_i / W_active``.  With no capacity configured (and no
    ``PATHWAY_TENANT_RPS``), the bucket tier is off and fairness acts
    through ordering + queue-full eviction alone."""

    def __init__(
        self,
        config: TenancyConfig,
        route: str = "/",
        capacity_rps: float | None = None,
    ):
        self.config = config
        self.route = route
        # explicit per-tenant rate (PATHWAY_TENANT_RPS, per weight
        # unit) beats the derived fair share when set
        self.capacity_rps = capacity_rps
        self._lock = threading.Lock()
        # insertion/touch order IS the LRU order (move_to_end on every
        # admit), so the state-cap eviction is O(1) — a min() scan over
        # 65536 entries under this lock would serialize the whole
        # route's admission behind it on every tail-tenant arrival
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        # exponentially-decayed active weight: W(t) = Σ wᵢ·e^(-(t-sᵢ)/τ)
        # where sᵢ is tenant i's last-seen instant.  Every term decays
        # with the SAME τ, so the aggregate decays uniformly — one
        # multiply per admission keeps it exact, no per-tenant scan,
        # and no cliff at any window boundary.
        self._active_tau = active_tau_s()
        self._active_weight = 0.0
        self._active_at = 0.0  # instant _active_weight was last decayed to
        self._vnow = 0.0
        self._admissions = 0  # deterministic counter the Fault Forge
        # flood= directive charges against (see testing/faults.py)
        self.labeler = TenantLabeler(config.metric_topn)
        from pathway_tpu.observability import REGISTRY
        from pathway_tpu.serving import metrics as _serving_metrics

        # tenant sheds also count on the route-level shed family, so
        # dashboards summing pathway_serving_shed_total see gate- and
        # replica-path tenant sheds alike
        self._m_route_shed = _serving_metrics.shed_counter()

        self._m_admitted = REGISTRY.counter(
            "pathway_tenant_admitted_total",
            "requests admitted past per-tenant fair admission, by route "
            "and tenant (top-N labels; the rest fold into __other__)",
            labelnames=("route", "tenant"),
        )
        self._m_shed = REGISTRY.counter(
            "pathway_tenant_shed_total",
            "requests shed charged to a tenant, by route/tenant/reason "
            "(tenant_rate = over fair share under pressure; tenant_evict "
            "= evicted from a full queue in favor of a colder tenant)",
            labelnames=("route", "tenant", "reason"),
        )
        self._m_wait = REGISTRY.histogram(
            "pathway_tenant_queue_wait_seconds",
            "admission-to-dispatch wait per tenant (top-N labels)",
            labelnames=("route", "tenant"),
        )
        self._m_staleness = REGISTRY.histogram(
            "pathway_tenant_staleness_seconds",
            "staleness of responses served per tenant (top-N labels) — "
            "replicas and cached router answers record here",
            labelnames=("tenant",),
            buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0),
        )

    # --- state ------------------------------------------------------------

    def _decay_to(self, now: float) -> None:
        """Uniform exponential decay of the active-weight aggregate:
        every tenant's contribution decays with the same τ, so decaying
        the SUM is exact.  Monotonic time only moves forward; a caller
        -injected older ``now`` (tests) is a no-op."""
        import math

        dt = now - self._active_at
        if dt > 0.0:
            self._active_weight *= math.exp(-dt / self._active_tau)
            self._active_at = now

    def _contribution(self, st: _TenantState, now: float) -> float:
        import math

        idle = max(now - st.last_seen, 0.0)
        return st.weight * math.exp(-idle / self._active_tau)

    def _state(self, tenant: str, weight: float, now: float) -> _TenantState:
        self._decay_to(now)
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= self.config.state_cap:
                # LRU bound: drop the least-recently-seen tenant (a
                # million-tenant population must not grow this dict
                # without bound); its bucket restarts full on return
                _victim, dropped = self._tenants.popitem(last=False)
                self._active_weight = max(
                    0.0,
                    self._active_weight - self._contribution(dropped, now),
                )
            st = _TenantState(now, weight, self.config.burst)
            self._tenants[tenant] = st
            self._active_weight += weight
        else:
            self._tenants.move_to_end(tenant)
            # refresh: replace the tenant's decayed contribution with
            # its full (possibly re-classed) weight — smooth at every
            # idle duration, no boundary to jump at
            self._active_weight += weight - self._contribution(st, now)
            st.weight = weight
            st.last_seen = now
        return st

    def fair_rate(self, weight: float) -> float | None:
        """This tenant's admitted-rate clamp (requests/s), or None when
        no capacity is configured (bucket tier off)."""
        if self.config.tenant_rps is not None:
            return self.config.tenant_rps * weight
        if self.capacity_rps is None:
            return None
        active = max(self._active_weight, weight)
        return self.capacity_rps * weight / active

    # --- admission --------------------------------------------------------

    def admit(
        self,
        tenant: str | None,
        tenant_class: str | None = None,
        now: float | None = None,
        *,
        pressure: bool = True,
        charge_only: bool = False,
    ) -> float:
        """Charge one request to ``tenant`` and return its WFQ
        virtual-finish tag (the micro-batcher's primary order key).

        Raises ``ShedError(429, "tenant_rate")`` when the tenant is
        over its fair share while the endpoint is under pressure.
        ``charge_only`` skips the shed (Fault Forge flood charging:
        drain the bucket + advance virtual time, never raise)."""
        from pathway_tpu.serving.admission import ShedError

        if now is None:
            now = time.monotonic()
        if tenant is None:
            tenant = ""
        weight = self.config.weight_of(tenant_class)
        with self._lock:
            n = 0
            if not charge_only:
                # the REAL-admission counter (synthetic flood charges
                # never advance it, or the flood would feed itself)
                self._admissions += 1
                n = self._admissions
            st = self._state(tenant, weight, now)
            rate = self.fair_rate(weight)
            shed_wait = 0.0
            if rate is not None:
                burst = max(self.config.burst, 1.0)
                st.tokens = min(
                    burst, st.tokens + (now - st.last_refill) * rate
                )
                st.last_refill = now
                if st.tokens >= 1.0:
                    st.tokens -= 1.0
                elif pressure and not charge_only:
                    shed_wait = (1.0 - st.tokens) / max(rate, 1e-9)
                else:
                    st.tokens = max(0.0, st.tokens - 1.0)
            # WFQ virtual time (start-time fair queueing): service one
            # unit costs 1/weight; the floor at vnow — which advances
            # only at DISPATCH (note_dispatched) — keeps an idle tenant
            # from banking credit while letting a fresh tenant's first
            # request order AHEAD of a hot tenant's queued backlog
            vstart = max(self._vnow, st.vfinish)
            st.vfinish = vstart + 1.0 / weight
            tag = st.vfinish
        label = self.labeler.label(tenant)
        if charge_only:
            return tag
        self._apply_flood(n, now)
        if shed_wait > 0.0:
            self._m_shed.labels(self.route, label, "tenant_rate").inc()
            self._m_route_shed.labels(self.route, "tenant_rate").inc()
            raise ShedError(429, "tenant_rate", min(shed_wait, 30.0))
        return tag

    def commit(self, tenant: str | None) -> None:
        """Count one admission AFTER the shared path accepted it — a
        request charged here and then shed as queue_full/concurrency/
        rate_limit was never admitted and must not inflate the
        per-tenant admitted series (callers pair this with
        :meth:`refund` on the shed branch)."""
        label = self.labeler.peek(tenant or "")
        self._m_admitted.labels(self.route, label).inc()

    def refund(
        self,
        tenant: str | None,
        tenant_class: str | None = None,
        tag: float | None = None,
    ) -> None:
        """Compensate an :meth:`admit` charge whose request was then
        shed on the SHARED admission path: it never entered the queue,
        so the tenant gets its fair-share token back and — when no
        later request advanced it further — its WFQ clock rolls back.
        Without this, a tenant retrying into a full queue drains its
        own bucket on requests that were never enqueued and sheds
        ``tenant_rate`` the moment capacity frees."""
        if tenant is None:
            tenant = ""
        weight = self.config.weight_of(tenant_class)
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return
            if self.fair_rate(weight) is not None:
                st.tokens = min(
                    max(self.config.burst, 1.0), st.tokens + 1.0
                )
            if tag is not None and st.vfinish == tag:
                st.vfinish = max(0.0, tag - 1.0 / weight)

    def _apply_flood(self, admission_n: int, now: float) -> None:
        """Fault Forge noisy-neighbor injection: deterministic synthetic
        charges keyed to the admission counter (no wall clock)."""
        from pathway_tpu.testing import faults

        plan = faults.active()
        if plan is None:
            return
        for tenant, cls, rps in plan.flood_charges(admission_n):
            for _ in range(rps):
                self.admit(tenant, cls, now, pressure=True, charge_only=True)

    def note_dispatched(self, order: Any) -> None:
        """Advance virtual time to the newest dispatched request's
        finish tag (the gate calls this per released request).  Tags of
        later arrivals floor here, so a tenant that was idle through a
        busy period cannot claim the virtual past."""
        tag = order[0] if isinstance(order, tuple) else None
        if tag is None:
            return
        with self._lock:
            if tag > self._vnow:
                self._vnow = tag

    # --- queue-full eviction ----------------------------------------------

    def pick_victim(self, queued: list, arriving_tag: float) -> Any:
        """Given the batcher's queued requests, return the one to evict
        in favor of an arrival carrying ``arriving_tag`` — the request
        whose tenant is MOST over its fair share (max virtual-finish
        tag), but only when strictly hotter than the arrival.  None =
        the arrival itself is the hottest; shed it normally."""
        victim = None
        victim_tag = arriving_tag
        for req in queued:
            order = getattr(req, "order", None)
            tag = order[0] if isinstance(order, tuple) else None
            if tag is not None and tag > victim_tag:
                victim, victim_tag = req, tag
        return victim

    # --- metrics hooks ----------------------------------------------------

    def count_evicted(self, tenant: str | None) -> None:
        label = self.labeler.label(tenant or "")
        self._m_shed.labels(self.route, label, "tenant_evict").inc()
        self._m_route_shed.labels(self.route, "tenant_evict").inc()

    def observe_wait(self, tenant: str | None, seconds: float) -> None:
        label = self.labeler.label(tenant or "")
        self._m_wait.labels(self.route, label).observe(max(0.0, seconds))

    def observe_staleness(
        self, tenant: str | None, seconds: float | None
    ) -> None:
        if seconds is None:
            return
        label = self.labeler.label(tenant or "")
        self._m_staleness.labels(label).observe(max(0.0, seconds))

    # --- introspection (tests / debug) ------------------------------------

    @property
    def tracked_tenants(self) -> int:
        with self._lock:
            return len(self._tenants)

    def active_weight(self, now: float | None = None) -> float:
        """The decayed fair-share denominator as of ``now`` (default:
        the monotonic clock) — tests inject times to pin the no-cliff
        contract."""
        with self._lock:
            if now is None:
                now = time.monotonic()
            self._decay_to(now)
            return self._active_weight


def ledger_for(
    qos: Any, route: str = "/", config: TenancyConfig | None = None
) -> TenantLedger | None:
    """The route's tenant ledger when ``PATHWAY_TENANT_QOS=1`` (or an
    explicit config is passed), else None — the total escape hatch:
    a None ledger means not one tenancy branch executes anywhere."""
    if config is None:
        if not tenancy_enabled_via_env():
            return None
        config = TenancyConfig()
    capacity = getattr(qos, "rate_limit_rps", None) if qos is not None else None
    return TenantLedger(config, route=route, capacity_rps=capacity)
