"""Shared Surge Gate metrics on the process-wide Flight Recorder
registry. Get-or-create accessors so the gate, the embedder and the KNN
index all record into ONE family (labeled by stage/route) regardless of
construction order."""

from __future__ import annotations

from pathway_tpu.observability import REGISTRY

_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def shed_counter():
    """Requests refused admission, by route and shed reason."""
    return REGISTRY.counter(
        "pathway_serving_shed_total",
        "requests shed by the Surge Gate, by route and reason "
        "(queue_full, rate_limit, concurrency, draining, shutdown)",
        labelnames=("route", "reason"),
    )


def admitted_counter():
    return REGISTRY.counter(
        "pathway_serving_admitted_total",
        "requests admitted past the Surge Gate, by route",
        labelnames=("route",),
    )


def expired_counter():
    """Admitted work dropped because its deadline passed before the
    stage could run (stage: gate = dropped at flush, never dispatched;
    knn = dropped before the device search)."""
    return REGISTRY.counter(
        "pathway_serving_deadline_expired_total",
        "requests dropped after their deadline expired, by stage",
        labelnames=("stage",),
    )


def queue_depth_gauge():
    return REGISTRY.gauge(
        "pathway_serving_queue_depth",
        "requests admitted but not yet dispatched into the engine, "
        "by route",
        labelnames=("route",),
    )


def inflight_gauge():
    return REGISTRY.gauge(
        "pathway_serving_inflight",
        "requests in flight (admitted, response not yet sent), by route",
        labelnames=("route",),
    )


def queue_wait_histogram():
    return REGISTRY.histogram(
        "pathway_serving_queue_wait_seconds",
        "admission-to-dispatch wait inside the micro-batcher, by route",
        labelnames=("route",),
    )


def batch_rows_histogram():
    return REGISTRY.histogram(
        "pathway_serving_batch_rows",
        "requests released per micro-batch flush, by route",
        labelnames=("route",),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    )


def occupancy_histogram():
    """Realized rows / padded bucket rows per device batch. 1.0 = the
    batch exactly filled its bucket; low values = padding waste."""
    return REGISTRY.histogram(
        "pathway_serving_batch_occupancy_ratio",
        "realized batch rows over padded bucket rows, by stage and "
        "bucket size",
        labelnames=("stage", "bucket"),
        buckets=_OCCUPANCY_BUCKETS,
    )
