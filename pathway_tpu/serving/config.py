"""Surge Gate configuration.

One ``QoSConfig`` describes the serving QoS policy of a single REST
endpoint (each ``rest_connector`` route gets its own gate): how many
requests may queue, how they batch, what deadline budget they carry and
how overload is shed. Every knob has a ``PATHWAY_SERVING_*`` environment
override so a deployment can turn the gate on (and tune it) without
touching pipeline code — see ``QoSConfig.from_env``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields, replace

_ENV_PREFIX = "PATHWAY_SERVING_"

# env var name -> (dataclass field, parser)
_ENV_FIELDS = {
    "MAX_QUEUE": ("max_queue", int),
    "MAX_BATCH": ("max_batch_size", int),
    "MAX_WAIT_MS": ("max_wait_ms", float),
    "DEADLINE_MS": ("default_deadline_ms", float),
    "MAX_DEADLINE_MS": ("max_deadline_ms", float),
    "RPS": ("rate_limit_rps", float),
    "BURST": ("rate_limit_burst", float),
    "MAX_INFLIGHT": ("max_inflight", int),
    "MAX_DISPATCHED": ("max_dispatched", int),
    "PRIORITY": ("priority", str),
    "DRAIN_GRACE_S": ("drain_grace_s", float),
}

# only these may be cleared back to None with an empty env value
# (`PATHWAY_SERVING_RPS=`); for mandatory knobs an empty string means
# "no override", matching an unset variable
_NONEABLE_FIELDS = frozenset(
    ("rate_limit_rps", "rate_limit_burst", "max_inflight", "max_dispatched")
)


def serving_enabled_via_env() -> bool:
    """``PATHWAY_SERVING_ENABLED=1`` turns the gate on for every
    rest_connector that was not given an explicit ``qos=``."""
    return os.environ.get(_ENV_PREFIX + "ENABLED", "0").lower() in (
        "1",
        "true",
        "yes",
    )


def plane_knobs() -> dict[str, str]:
    """Snapshot of every ``PATHWAY_*`` knob set in this environment —
    the serving plane's metadata hook for static verification: the
    Plane Doctor (analysis/plane.py knob-coherence) lints this surface
    and ``python -m pathway_tpu.analysis --plane`` records it alongside
    its findings so CI logs show exactly which deployment the verdict
    applied to."""
    return {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith("PATHWAY_")
    }


def default_bucket_ladder(max_batch_size: int) -> tuple[int, ...]:
    """Power-of-two ladder capped at ``max_batch_size`` — matching the
    encoder's pad buckets (xpacks/llm/_encoder.py ``_bucket_batch``) so a
    released batch lands on a shape the jitted kernels already compiled."""
    ladder: list[int] = []
    b = 1
    while b < max_batch_size:
        ladder.append(b)
        b *= 2
    ladder.append(int(max_batch_size))
    return tuple(ladder)


@dataclass(frozen=True)
class QoSConfig:
    """Serving QoS policy for one REST endpoint.

    max_queue: admission bound — requests queued (admitted, not yet
        dispatched into the engine) beyond this shed with 429.
    max_batch_size / max_wait_ms: micro-batcher flush triggers — release
        a batch when this many requests coalesced, or when the oldest
        queued request has waited this long.
    batch_buckets: ladder of release sizes; ``None`` derives the
        power-of-two ladder from max_batch_size.
    default_deadline_ms / max_deadline_ms: deadline budget applied when
        the ``x-pathway-deadline-ms`` header is absent / the cap clamped
        onto client-supplied budgets.
    rate_limit_rps / rate_limit_burst: endpoint token bucket (None = no
        rate limit; burst defaults to max(rps, 1)).
    max_inflight: cap on requests concurrently in flight for this
        endpoint (queued + dispatched, until their response is sent).
    max_dispatched: pipeline-depth window — the batcher releases a new
        batch only while fewer than this many dispatched requests await
        their response, so a slow engine backs pressure up into the
        BOUNDED queue (where it sheds) instead of the unbounded
        InputSession. ``None`` derives ``2 * max_batch_size``.
    priority: "interactive" marks the gate's InputSession so the engine
        tick prefers it over bulk ingest sessions; "bulk" opts out.
    drain_grace_s: how long ``drain()`` waits for in-flight requests
        before giving up and shutting the webserver anyway.
    """

    max_queue: int = 256
    max_batch_size: int = 32
    max_wait_ms: float = 5.0
    batch_buckets: tuple[int, ...] | None = None
    default_deadline_ms: float = 30_000.0
    max_deadline_ms: float = 120_000.0
    rate_limit_rps: float | None = None
    rate_limit_burst: float | None = None
    max_inflight: int | None = None
    max_dispatched: int | None = None
    priority: str = "interactive"
    drain_grace_s: float = 10.0

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.priority not in ("interactive", "bulk"):
            raise ValueError("priority must be 'interactive' or 'bulk'")
        if self.batch_buckets is not None:
            bb = tuple(sorted(int(b) for b in self.batch_buckets))
            if not bb or bb[0] < 1:
                raise ValueError("batch_buckets must be positive ints")
            object.__setattr__(self, "batch_buckets", bb)

    def buckets(self) -> tuple[int, ...]:
        return self.batch_buckets or default_bucket_ladder(
            self.max_batch_size
        )

    def bucket_for(self, n: int) -> int:
        """Smallest ladder entry >= n (the shape a batch of n pads to);
        the top rung for oversized n."""
        for b in self.buckets():
            if b >= n:
                return b
        return self.buckets()[-1]

    def dispatch_window(self) -> int:
        if self.max_dispatched is not None:
            return max(int(self.max_dispatched), 1)
        return 2 * self.max_batch_size

    def burst(self) -> float:
        if self.rate_limit_burst is not None:
            return float(self.rate_limit_burst)
        return max(float(self.rate_limit_rps or 0.0), 1.0)

    @classmethod
    def from_env(cls, base: "QoSConfig | None" = None) -> "QoSConfig":
        """``base`` (default: all-defaults config) overridden by any
        ``PATHWAY_SERVING_*`` variables present in the environment."""
        cfg = base if base is not None else cls()
        overrides = {}
        valid = {f.name for f in fields(cls)}
        for env_name, (field_name, parser) in _ENV_FIELDS.items():
            raw = os.environ.get(_ENV_PREFIX + env_name)
            if raw is None or field_name not in valid:
                continue
            if raw == "":
                if field_name in _NONEABLE_FIELDS:
                    overrides[field_name] = None
                continue
            try:
                overrides[field_name] = parser(raw)
            except ValueError:
                raise ValueError(
                    f"{_ENV_PREFIX}{env_name}={raw!r} is not a valid "
                    f"{parser.__name__}"
                ) from None
        return replace(cfg, **overrides) if overrides else cfg
