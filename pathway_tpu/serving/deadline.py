"""Request-deadline registry — deadline propagation through the tick.

The REST ingress registers each admitted request's absolute deadline
(``time.monotonic()`` seconds) under the request's row key; batch-shaped
operators downstream (the micro-batcher at flush, the external-index
exec before a device search) consult it so work whose deadline already
expired is dropped instead of burning a batch slot. Mirrors the tracing
pending-request registry (observability/tracing.py) — module-level, lock
under a dict, ~zero cost while empty.
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_deadlines: dict[int, float] = {}

# entries this far past their deadline are garbage (their row either
# already ticked or will never tick); swept lazily on register so a
# handler that timed out (504) can leave its entry behind for the
# engine to observe without leaking it forever
_SWEEP_GRACE_S = 60.0


def register(key: int, deadline: float) -> None:
    now = time.monotonic()
    with _lock:
        if len(_deadlines) > 128:
            cutoff = now - _SWEEP_GRACE_S
            for k in [k for k, d in _deadlines.items() if d < cutoff]:
                del _deadlines[k]
        _deadlines[key] = deadline


def unregister(key: int) -> None:
    if not _deadlines:
        return
    with _lock:
        _deadlines.pop(key, None)


def expired(key: int, now: float | None = None) -> bool:
    """True only when the key carries a deadline AND it has passed —
    unknown keys (no gate, bulk rows) never read as expired."""
    if not _deadlines:  # fast path: no serving gate active
        return False
    with _lock:
        d = _deadlines.get(key)
    if d is None:
        return False
    return (time.monotonic() if now is None else now) > d


def remaining(key: int, now: float | None = None) -> float | None:
    """Seconds until the key's deadline (negative = expired); None when
    the key has no registered deadline."""
    if not _deadlines:
        return None
    with _lock:
        d = _deadlines.get(key)
    if d is None:
        return None
    return d - (time.monotonic() if now is None else now)


def active_count() -> int:
    with _lock:
        return len(_deadlines)
