"""Phoenix serving degradation — stale reads while the engine recovers.

While the process group is recovering (a peer died and the supervisor is
restarting the group, or this process is replaying persisted state after
a restart), the engine tick loop is not answering queries.  Instead of
letting admitted KNN/RAG reads 500 or time out, Surge-Gated endpoints
answer from the LAST HYDRATED INDEX SNAPSHOT: the ``ExternalIndexExec``
registers itself here as a stale-capable reader and bumps its freshness
clock every tick, persistence restore hydrates it up front (mmap), and
the REST handler (io/http/_server.py) detects recovery mode and serves
through the registered responder with explicit staleness headers:

* ``x-pathway-stale: true`` and ``x-pathway-staleness-seconds: <s>`` on
  every degraded response;
* the ``x-pathway-max-staleness-ms`` REQUEST header bounds acceptable
  staleness — a stale snapshot older than the bound sheds with 503 +
  Retry-After instead of silently serving garbage.

Observability: ``pathway_serving_staleness_seconds`` (gauge, scrape-time
freshness of the newest registered index), ``pathway_serving_stale_
served_total`` and ``pathway_serving_degraded_shed_total`` counters.

Everything is process-global and thread-safe: recovery is entered from
mesh failure-listener threads and persistence attach, read from aiohttp
handler threads.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable

_lock = threading.Lock()
_reasons: dict[str, float] = {}  # active recovery reasons -> entered_at
_responders: dict[str, Callable[[dict], Any]] = {}  # route -> responder
_index_readers: list = []  # weakrefs to stale-capable index execs
_fresh_at: float | None = None  # monotonic instant of last engine tick
# serializes stale searches against engine-side index mutation (replay
# ticks rebuild the corpus while the handler reads it)
index_guard = threading.RLock()

_M: dict | None = None


def _metrics() -> dict:
    global _M
    if _M is None:
        from pathway_tpu.observability import REGISTRY

        gauge = REGISTRY.gauge(
            "pathway_serving_staleness_seconds",
            "age of the snapshot serving reads: seconds since the last "
            "engine tick refreshed the index (0 while live)",
        )
        gauge.set_function(lambda: staleness_seconds() or 0.0)
        _M = {
            "staleness": gauge,
            "stale_served": REGISTRY.counter(
                "pathway_serving_stale_served_total",
                "requests answered from the last hydrated index snapshot "
                "while the engine was recovering, by route",
                labelnames=("route",),
            ),
            "degraded_shed": REGISTRY.counter(
                "pathway_serving_degraded_shed_total",
                "requests shed during recovery, by route and reason "
                "(max_staleness = snapshot older than the request's "
                "x-pathway-max-staleness-ms; no_responder = endpoint "
                "has no stale read path)",
                labelnames=("route", "reason"),
            ),
        }
    return _M


# --- recovery state -------------------------------------------------------


def enter_recovery(reason: str) -> None:
    """Mark the engine as recovering; idempotent per reason. Reasons
    stack: replay inside a peer-failure window clears independently."""
    _metrics()
    with _lock:
        _reasons.setdefault(reason, time.monotonic())


def exit_recovery(reason: str | None = None) -> None:
    """Clear one recovery reason (or all, when None)."""
    with _lock:
        if reason is None:
            _reasons.clear()
        else:
            _reasons.pop(reason, None)


def recovering() -> str | None:
    """The oldest active recovery reason, or None when the engine is
    live."""
    with _lock:
        if not _reasons:
            return None
        return min(_reasons, key=_reasons.__getitem__)


# --- freshness ------------------------------------------------------------


def mark_fresh() -> None:
    """Called by index execs on every engine tick that could have
    refreshed them: the staleness clock restarts."""
    global _fresh_at
    _fresh_at = time.monotonic()


def staleness_seconds() -> float | None:
    """Seconds since the engine last refreshed the serving indexes, or
    None when no index ever registered. Live engines report ~0."""
    if _fresh_at is None:
        return None
    return max(0.0, time.monotonic() - _fresh_at)


# --- stale read paths -----------------------------------------------------


def register_index_reader(exec_obj: Any) -> None:
    """Register a stale-capable index exec (weakly): generic responders
    can answer ``search`` against the last hydrated corpus."""
    with _lock:
        _index_readers[:] = [r for r in _index_readers if r() is not None]
        _index_readers.append(weakref.ref(exec_obj))
    mark_fresh()


def stale_knn_search(
    triples: list[tuple[Any, int, Any]],
) -> list[tuple[tuple[int, float], ...]]:
    """Answer KNN queries against the most recently registered index's
    current (possibly stale) corpus. Raises RuntimeError when no index
    is registered."""
    with _lock:
        readers = [r() for r in _index_readers]
    for reader in reversed(readers):
        if reader is not None:
            with index_guard:
                return reader.index.search(triples)
    raise RuntimeError("no stale-capable index registered")


def register_stale_responder(
    route: str, fn: Callable[[dict], Any]
) -> None:
    """Register the degraded-mode answer function for a REST route:
    ``fn(request_values) -> json-able payload``, executed on a worker
    thread while the engine recovers. Typically closes over
    :func:`stale_knn_search` plus the app's response formatting."""
    _metrics()
    with _lock:
        _responders[route] = fn


def stale_responder(route: str) -> Callable[[dict], Any] | None:
    with _lock:
        return _responders.get(route)


def count_stale_served(route: str) -> None:
    _metrics()["stale_served"].labels(route).inc()


def count_degraded_shed(route: str, reason: str) -> None:
    _metrics()["degraded_shed"].labels(route, reason).inc()


def reset() -> None:
    """Test hook: clear recovery state, responders and readers."""
    global _fresh_at
    with _lock:
        _reasons.clear()
        _responders.clear()
        _index_readers.clear()
    _fresh_at = None
