"""Deadline-aware admission control: the decision whether a request may
enter the serving queue at all.

Checks run in shed-priority order — draining beats everything (the
endpoint is going away), then the queue bound (the overload signal),
then the per-endpoint concurrency cap, then the token-bucket rate
limit. Every refusal carries an HTTP status and a ``Retry-After`` hint
so clients back off instead of hammering a saturated endpoint.
"""

from __future__ import annotations

import threading
import time
import weakref

from pathway_tpu.serving import metrics as _metrics
from pathway_tpu.serving.config import QoSConfig


class ShedError(Exception):
    """Request refused admission — explicit load shedding."""

    def __init__(self, status: int, reason: str, retry_after_s: float):
        super().__init__(f"shed ({reason}): retry after {retry_after_s:.3f}s")
        self.status = status
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeadlineExceeded(Exception):
    """The request's deadline passed before its work could run."""


class TokenBucket:
    """Monotonic-clock token bucket; not thread-safe by itself (the
    admission controller serializes access)."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self._last = time.monotonic()

    def try_acquire(self, now: float | None = None) -> float:
        """0.0 = token taken; otherwise seconds until one accrues."""
        if now is None:
            now = time.monotonic()
        self.tokens = min(
            self.burst, self.tokens + (now - self._last) * self.rate
        )
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


# seconds a shared-path shed keeps the endpoint "under pressure" for
# the tenant fair-share clamp.  The instantaneous signal alone
# oscillates under sustained overload (the shared bucket saw-tooths
# around one accrued token), and every pressure-False instant would let
# the hot tenant through the tenant gate to steal the fresh token —
# stickiness keeps the clamp engaged while the endpoint actually sheds,
# and relaxes within a second of the overload ending.
PRESSURE_STICKY_S = 1.0


class AdmissionController:
    """Per-endpoint admission state: queue depth, in-flight count, rate
    limiter, drain flag. ``admit`` raises ``ShedError``; callers pair it
    with ``on_flushed`` (requests left the queue) and ``complete`` (the
    response went out)."""

    def __init__(self, config: QoSConfig, route: str = "/", ledger=None):
        self.config = config
        self.route = route
        # Tenant Weave: an optional serving.tenancy.TenantLedger makes
        # admission tenant-aware — per-tenant fair-share buckets shed
        # the over-share tenant (429 tenant_rate) BEFORE it can drain
        # the shared queue/bucket.  None (the default) keeps this
        # controller byte-identical to the tenant-blind path.  A
        # SurgeGate drives its ledger itself (it also needs the WFQ
        # ordering tag and queue-full eviction); replicas pass one here.
        self.ledger = ledger
        self._lock = threading.Lock()
        self.queued = 0
        self.inflight = 0
        self.draining = False
        self._bucket = (
            TokenBucket(config.rate_limit_rps, config.burst())
            if config.rate_limit_rps
            else None
        )
        self._idle = threading.Event()
        self._idle.set()
        self._pressure_at: float | None = None  # last shared-path shed
        self._m_shed = _metrics.shed_counter()
        self._m_admitted = _metrics.admitted_counter().labels(route)
        # the process-wide registry holds these callbacks forever: keep
        # the controller weakly referenced so a torn-down endpoint's
        # admission state can be collected (the gauge then reads 0)
        ref = weakref.ref(self)

        def _queued_now() -> int:
            ctl = ref()
            return ctl.queued if ctl is not None else 0

        def _inflight_now() -> int:
            ctl = ref()
            return ctl.inflight if ctl is not None else 0

        _metrics.queue_depth_gauge().labels(route).set_function(_queued_now)
        _metrics.inflight_gauge().labels(route).set_function(_inflight_now)

    def _shed(
        self,
        status: int,
        reason: str,
        retry_after_s: float,
        now: float | None = None,
    ):
        self._pressure_at = time.monotonic() if now is None else now
        self._m_shed.labels(self.route, reason).inc()
        raise ShedError(status, reason, retry_after_s)

    def under_pressure(self, now: float | None = None) -> bool:
        """Contention signal for the tenant fair-share clamp: the
        endpoint shed on the shared path within the last
        ``PRESSURE_STICKY_S`` seconds, the shared token bucket is
        (about to be) empty, or the queue is half full.  While False
        the per-tenant buckets stay dormant — fair admission is
        work-conserving, a lone hot tenant on an idle endpoint keeps
        its full throughput."""
        if now is None:
            now = time.monotonic()
        if (
            self._pressure_at is not None
            and now - self._pressure_at < PRESSURE_STICKY_S
        ):
            return True
        if self.queued >= max(1, self.config.max_queue // 2):
            return True
        b = self._bucket
        if b is None:
            return False
        # read-only refill projection (consume nothing)
        return min(b.burst, b.tokens + (now - b._last) * b.rate) < 1.0

    def headroom_besides_queue(self, now: float | None = None) -> bool:
        """True when the queue bound is the ONLY thing that would shed
        an arrival right now.  The gate's queue-full tenant eviction
        gates on this: destroying a queued (already-admitted) request
        in exchange for an arrival the bucket or concurrency cap would
        shed anyway loses BOTH requests."""
        if self.draining:
            return False
        cfg = self.config
        if (
            cfg.max_inflight is not None
            and self.inflight >= cfg.max_inflight
        ):
            return False
        b = self._bucket
        if b is None:
            return True
        if now is None:
            now = time.monotonic()
        # read-only projection; the admit that follows consumes the
        # real token (a lost race costs one extra eviction, bounded)
        return min(b.burst, b.tokens + (now - b._last) * b.rate) >= 1.0

    def admit(
        self,
        now: float | None = None,
        tenant: str | None = None,
        tenant_class: str | None = None,
    ) -> None:
        cfg = self.config
        if now is None:
            now = time.monotonic()
        tag = None
        if self.ledger is not None:
            # per-tenant fair share first: a shed here is charged to
            # the hot tenant and never consumes a shared bucket token
            # (the ledger itself counts it on the route-level shed
            # family, so gate- and replica-path sheds report alike)
            tag = self.ledger.admit(
                tenant,
                tenant_class,
                now,
                pressure=self.under_pressure(now),
            )
        try:
            with self._lock:
                if self.draining:
                    self._shed(503, "draining", cfg.drain_grace_s, now)
                if self.queued >= cfg.max_queue:
                    # the queue clears one micro-batch per flush window —
                    # hint a backoff of one full wait window
                    self._shed(
                        429,
                        "queue_full",
                        max(cfg.max_wait_ms / 1000.0, 0.05),
                        now,
                    )
                if (
                    cfg.max_inflight is not None
                    and self.inflight >= cfg.max_inflight
                ):
                    self._shed(
                        429,
                        "concurrency",
                        max(cfg.max_wait_ms / 1000.0, 0.05),
                        now,
                    )
                if self._bucket is not None:
                    wait = self._bucket.try_acquire(now)
                    if wait > 0.0:
                        self._shed(429, "rate_limit", wait, now)
                self.queued += 1
                self.inflight += 1
                self._idle.clear()
        except ShedError:
            if self.ledger is not None:
                # shed on the SHARED path: the request never entered
                # the queue, so the tenant's fair-share charge comes
                # back (see TenantLedger.refund)
                self.ledger.refund(tenant, tenant_class, tag)
            raise
        if self.ledger is not None:
            self.ledger.commit(tenant)
        self._m_admitted.inc()

    def on_flushed(self, n: int) -> None:
        with self._lock:
            self.queued = max(0, self.queued - n)

    def complete(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            if self.inflight == 0:
                self._idle.set()

    def start_drain(self) -> None:
        with self._lock:
            self.draining = True
            if self.inflight == 0:
                self._idle.set()

    def wait_idle(self, timeout: float | None) -> bool:
        """Block until no request is in flight (drain helper)."""
        return self._idle.wait(timeout)
