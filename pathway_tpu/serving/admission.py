"""Deadline-aware admission control: the decision whether a request may
enter the serving queue at all.

Checks run in shed-priority order — draining beats everything (the
endpoint is going away), then the queue bound (the overload signal),
then the per-endpoint concurrency cap, then the token-bucket rate
limit. Every refusal carries an HTTP status and a ``Retry-After`` hint
so clients back off instead of hammering a saturated endpoint.
"""

from __future__ import annotations

import threading
import time
import weakref

from pathway_tpu.serving import metrics as _metrics
from pathway_tpu.serving.config import QoSConfig


class ShedError(Exception):
    """Request refused admission — explicit load shedding."""

    def __init__(self, status: int, reason: str, retry_after_s: float):
        super().__init__(f"shed ({reason}): retry after {retry_after_s:.3f}s")
        self.status = status
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeadlineExceeded(Exception):
    """The request's deadline passed before its work could run."""


class TokenBucket:
    """Monotonic-clock token bucket; not thread-safe by itself (the
    admission controller serializes access)."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self._last = time.monotonic()

    def try_acquire(self, now: float | None = None) -> float:
        """0.0 = token taken; otherwise seconds until one accrues."""
        if now is None:
            now = time.monotonic()
        self.tokens = min(
            self.burst, self.tokens + (now - self._last) * self.rate
        )
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


class AdmissionController:
    """Per-endpoint admission state: queue depth, in-flight count, rate
    limiter, drain flag. ``admit`` raises ``ShedError``; callers pair it
    with ``on_flushed`` (requests left the queue) and ``complete`` (the
    response went out)."""

    def __init__(self, config: QoSConfig, route: str = "/"):
        self.config = config
        self.route = route
        self._lock = threading.Lock()
        self.queued = 0
        self.inflight = 0
        self.draining = False
        self._bucket = (
            TokenBucket(config.rate_limit_rps, config.burst())
            if config.rate_limit_rps
            else None
        )
        self._idle = threading.Event()
        self._idle.set()
        self._m_shed = _metrics.shed_counter()
        self._m_admitted = _metrics.admitted_counter().labels(route)
        # the process-wide registry holds these callbacks forever: keep
        # the controller weakly referenced so a torn-down endpoint's
        # admission state can be collected (the gauge then reads 0)
        ref = weakref.ref(self)

        def _queued_now() -> int:
            ctl = ref()
            return ctl.queued if ctl is not None else 0

        def _inflight_now() -> int:
            ctl = ref()
            return ctl.inflight if ctl is not None else 0

        _metrics.queue_depth_gauge().labels(route).set_function(_queued_now)
        _metrics.inflight_gauge().labels(route).set_function(_inflight_now)

    def _shed(self, status: int, reason: str, retry_after_s: float):
        self._m_shed.labels(self.route, reason).inc()
        raise ShedError(status, reason, retry_after_s)

    def admit(self, now: float | None = None) -> None:
        cfg = self.config
        with self._lock:
            if self.draining:
                self._shed(503, "draining", cfg.drain_grace_s)
            if self.queued >= cfg.max_queue:
                # the queue clears one micro-batch per flush window —
                # hint a backoff of one full wait window
                self._shed(
                    429, "queue_full", max(cfg.max_wait_ms / 1000.0, 0.05)
                )
            if (
                cfg.max_inflight is not None
                and self.inflight >= cfg.max_inflight
            ):
                self._shed(
                    429, "concurrency", max(cfg.max_wait_ms / 1000.0, 0.05)
                )
            if self._bucket is not None:
                wait = self._bucket.try_acquire(now)
                if wait > 0.0:
                    self._shed(429, "rate_limit", wait)
            self.queued += 1
            self.inflight += 1
            self._idle.clear()
        self._m_admitted.inc()

    def on_flushed(self, n: int) -> None:
        with self._lock:
            self.queued = max(0, self.queued - n)

    def complete(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            if self.inflight == 0:
                self._idle.set()

    def start_drain(self) -> None:
        with self._lock:
            self.draining = True
            if self.inflight == 0:
                self._idle.set()

    def wait_idle(self, timeout: float | None) -> bool:
        """Block until no request is in flight (drain helper)."""
        return self._idle.wait(timeout)
