"""Replica Shield failover router — deadline-aware, occupancy-weighted
balancing over the read replicas, IN FRONT of each replica's Surge Gate.

The router is a thin asyncio HTTP proxy holding no index state: it
forwards each read to the best-qualified replica and turns replica
failure into a retry instead of a client-visible error.

Routing policy (per request):

* **Qualify** — a replica is eligible when it is not ejected, reports
  ``ready`` (caught up with the writer since its current subscription —
  a restarted replica is only re-admitted once it clears this
  freshness bound) and, when the request carries
  ``x-pathway-max-staleness-ms``, its last reported staleness fits the
  bound.  The replica re-checks the bound locally at serve time, so a
  stale-between-polls replica answers 503 and the router moves on.
* **Degrade before shed** — when no replica is fresh but some are alive
  and the request did NOT bound staleness, the router serves from a
  stale replica (PR 8's stale-responder contract: explicit
  ``x-pathway-stale`` headers, never silent).  Explicit 503 +
  ``Retry-After`` goes out only when NO replica qualifies at all.
* **Pick** — occupancy-weighted: fewest in-flight (router-side counter
  + the replica's reported admission occupancy), EWMA latency as the
  tie-break.
* **Retry** — a transport failure (dead replica: connection refused /
  reset mid-response) ejects the replica, fires failure listeners
  (the HostMesh ``add_failure_listener`` contract), and retries the
  SAME request on a different replica within the ORIGINAL deadline —
  never the ejected one, at most ``PATHWAY_SERVING_RETRIES`` (default
  1) extra attempts.  Every attempt is a ``router.attempt`` child span,
  so the retry hop is visible in the stitched trace.
* **Hedge** — with ``PATHWAY_SERVING_HEDGE_MS`` set, a primary attempt
  that has not answered within the hedge budget gets a duplicate on a
  second replica; the first response wins and the loser is cancelled
  (duplicate-suppressed — reads are idempotent, exactly one response
  reaches the client).

Health: a background poller GETs every replica's ``/replica/health``
each ``PATHWAY_ROUTER_HEALTH_MS`` (heartbeat analog); consecutive
misses eject.  Ejected replicas keep being polled and re-admit only
once they report ``ready`` again.

Deadlines: ``x-pathway-deadline-ms`` propagates with the REMAINING
budget per attempt, so a retried request never outlives its original
deadline, and the trace context rides ``traceparent`` end to end.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from typing import Any, Callable

_FWD_HEADERS = (
    # request headers forwarded to the replica verbatim
    "x-pathway-max-staleness-ms",
    "content-type",
)
_BACK_HEADERS = (
    # response headers surfaced back to the client
    "x-pathway-replica",
    "x-pathway-applied-tick",
    "x-pathway-staleness-seconds",
    "x-pathway-stale",
    "retry-after",
    "content-type",
)


def replicas_from_env() -> list[str]:
    """PATHWAY_SERVING_REPLICAS: comma-separated replica base URLs
    (e.g. ``http://127.0.0.1:9101,http://127.0.0.1:9102``)."""
    raw = os.environ.get("PATHWAY_SERVING_REPLICAS", "")
    return [u.strip().rstrip("/") for u in raw.split(",") if u.strip()]


def hedge_ms_env() -> float:
    raw = os.environ.get("PATHWAY_SERVING_HEDGE_MS", "") or "0"
    try:
        return max(float(raw), 0.0)
    except ValueError:
        raise ValueError(
            f"PATHWAY_SERVING_HEDGE_MS={raw!r} is not a number"
        ) from None


class _Transport(Exception):
    """Replica transport failure (dead/unreachable) — retryable."""


class ReplicaEndpoint:
    """Router-side view of one replica: URL + health + occupancy."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.inflight = 0  # router-side in-flight (attempts)
        self.reported_inflight = 0  # replica's admission occupancy
        self.ewma_ms = 0.0
        self.applied_tick = -1
        self.staleness_s: float | None = None
        self.ready = False
        self.alive = False  # last health poll answered
        self.ejected = False
        self.eject_reason = ""
        self.misses = 0

    def score(self) -> tuple:
        return (
            self.inflight + self.reported_inflight,
            self.ewma_ms,
            random.random(),
        )

    def qualifies(self, max_staleness_ms: float | None) -> bool:
        if self.ejected or not self.ready:
            return False
        if max_staleness_ms is None:
            return True
        s = self.staleness_s
        return s is not None and s * 1000.0 <= max_staleness_ms

    def serves_stale(self) -> bool:
        """Degraded tier: alive (answers health) but not fresh."""
        return self.alive and not self.ejected


class FailoverRouter:
    def __init__(
        self,
        replicas: list[str] | None = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        retries: int | None = None,
        hedge_ms: float | None = None,
        health_interval_ms: float | None = None,
        liveness_misses: int = 3,
        default_deadline_ms: float = 30_000.0,
        max_deadline_ms: float = 120_000.0,
    ):
        urls = replicas if replicas is not None else replicas_from_env()
        if not urls:
            raise ValueError(
                "FailoverRouter needs at least one replica URL (pass "
                "replicas=[...] or set PATHWAY_SERVING_REPLICAS)"
            )
        self.endpoints = [
            ReplicaEndpoint(f"replica{i}", u) for i, u in enumerate(urls)
        ]
        self.host = host
        self.port = port
        if retries is None:
            retries = int(os.environ.get("PATHWAY_SERVING_RETRIES", "1") or 1)
        self.retries = max(int(retries), 0)
        self.hedge_s = (
            hedge_ms_env() if hedge_ms is None else max(float(hedge_ms), 0.0)
        ) / 1000.0
        if health_interval_ms is None:
            health_interval_ms = float(
                os.environ.get("PATHWAY_ROUTER_HEALTH_MS", "250") or 250
            )
        self.health_interval_s = max(health_interval_ms, 20.0) / 1000.0
        self.liveness_misses = max(int(liveness_misses), 1)
        self.default_deadline_ms = float(default_deadline_ms)
        self.max_deadline_ms = float(max_deadline_ms)
        self._lock = threading.Lock()
        self._failure_listeners: list[Callable[[str, str], None]] = []
        self._past_failures: list[tuple[str, str]] = []
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_ready = threading.Event()
        self._bound = threading.Event()
        self._stop_async: Any = None
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopped = False
        from pathway_tpu.observability import REGISTRY

        self._m_requests = REGISTRY.counter(
            "pathway_router_requests_total",
            "routed read requests, by chosen replica and outcome "
            "(ok / shed / stale_shed / error / no_replica)",
            labelnames=("replica", "outcome"),
        )
        self._m_retries = REGISTRY.counter(
            "pathway_router_retries_total",
            "same-deadline retries after a replica failed mid-request",
        )
        self._m_hedges = REGISTRY.counter(
            "pathway_router_hedges_total",
            "hedged duplicates fired after PATHWAY_SERVING_HEDGE_MS, by "
            "which attempt won",
            labelnames=("winner",),
        )
        self._m_ejections = REGISTRY.counter(
            "pathway_router_ejections_total",
            "replica ejections, by replica and reason",
            labelnames=("replica", "reason"),
        )
        self._m_inflight = REGISTRY.gauge(
            "pathway_router_replica_inflight",
            "router-side in-flight attempts per replica",
            labelnames=("replica",),
        )
        for ep in self.endpoints:
            self._m_inflight.labels(ep.name).set_function(
                lambda ep=ep: ep.inflight
            )

    # --- failure listeners (HostMesh contract) ----------------------------

    def add_failure_listener(self, fn: Callable[[str, str], None]) -> None:
        """``fn(replica_name, reason)`` fires at ejection; late
        registrants replay past ejections (mesh parity)."""
        with self._lock:
            self._failure_listeners.append(fn)
            past = list(self._past_failures)
        for name, reason in past:
            try:
                fn(name, reason)
            except Exception:
                pass

    def _eject(self, ep: ReplicaEndpoint, reason: str) -> None:
        with self._lock:
            if ep.ejected:
                return
            ep.ejected = True
            ep.ready = False
            ep.eject_reason = reason
            listeners = list(self._failure_listeners)
            self._past_failures.append((ep.name, reason))
        self._m_ejections.labels(ep.name, reason.split(":")[0]).inc()
        import logging

        logging.getLogger("pathway_tpu").warning(
            "router: ejected %s (%s)", ep.name, reason
        )
        for fn in listeners:
            try:
                fn(ep.name, reason)
            except Exception:
                pass

    def _readmit(self, ep: ReplicaEndpoint) -> None:
        with self._lock:
            if not ep.ejected:
                return
            ep.ejected = False
            ep.eject_reason = ""
        import logging

        logging.getLogger("pathway_tpu").info(
            "router: re-admitted %s (fresh at tick %d)",
            ep.name,
            ep.applied_tick,
        )

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "FailoverRouter":
        if self._started:
            return self
        self._started = True
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="pw-router"
        )
        self._thread.start()
        self._bound.wait(30.0)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started or self._stopped:
            return
        self._stopped = True
        self._loop_ready.wait(timeout)
        stop_async = self._stop_async
        if stop_async is not None:
            try:
                stop_async()
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout)

    def _run(self) -> None:
        import aiohttp
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        stop_ev = asyncio.Event()
        self._stop_async = lambda: loop.call_soon_threadsafe(stop_ev.set)
        self._loop_ready.set()

        async def main():
            self._session = aiohttp.ClientSession()
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.port = (
                runner.addresses[0][1] if runner.addresses else self.port
            )
            self._bound.set()
            poller = asyncio.ensure_future(self._health_loop())
            if not self._stopped:
                await stop_ev.wait()
            poller.cancel()
            await self._session.close()
            await runner.cleanup()

        try:
            loop.run_until_complete(main())
        finally:
            self._bound.set()
            loop.close()

    # --- health -----------------------------------------------------------

    async def _health_loop(self) -> None:
        import aiohttp

        while True:
            for ep in self.endpoints:
                try:
                    async with self._session.get(
                        ep.url + "/replica/health",
                        timeout=aiohttp.ClientTimeout(total=1.0),
                    ) as resp:
                        h = await resp.json()
                    ep.alive = True
                    ep.misses = 0
                    ep.applied_tick = int(h.get("applied_tick", -1))
                    s = h.get("staleness_seconds")
                    ep.staleness_s = None if s is None else float(s)
                    ep.reported_inflight = int(h.get("inflight", 0))
                    was_ready = ep.ready
                    ep.ready = bool(h.get("ready", False))
                    if ep.ejected and ep.ready:
                        # the freshness bound for re-admission: the
                        # replica reports caught-up again
                        self._readmit(ep)
                    del was_ready
                except asyncio.CancelledError:
                    raise
                except Exception:
                    ep.misses += 1
                    ep.alive = False
                    ep.ready = False
                    if ep.misses >= self.liveness_misses and not ep.ejected:
                        self._eject(
                            ep,
                            f"liveness: {ep.misses} consecutive health "
                            "probes failed",
                        )
            await asyncio.sleep(self.health_interval_s)

    # --- request path -----------------------------------------------------

    def _deadline_budget_s(self, request) -> float:
        import math

        raw = request.headers.get("x-pathway-deadline-ms")
        budget_ms = None
        if raw is not None:
            try:
                budget_ms = float(raw)
            except ValueError:
                budget_ms = None
            if budget_ms is not None and not math.isfinite(budget_ms):
                budget_ms = None
        if budget_ms is None:
            budget_ms = self.default_deadline_ms
        return min(budget_ms, self.max_deadline_ms) / 1000.0

    @staticmethod
    def _max_staleness_ms(request) -> float | None:
        import math

        raw = request.headers.get("x-pathway-max-staleness-ms")
        if raw is None:
            return None
        try:
            v = float(raw)
        except ValueError:
            return None
        return v if math.isfinite(v) else None

    def _candidates(
        self, max_staleness_ms: float | None, tried: set
    ) -> list[ReplicaEndpoint]:
        fresh = [
            ep
            for ep in self.endpoints
            if ep.name not in tried and ep.qualifies(max_staleness_ms)
        ]
        if fresh:
            return sorted(fresh, key=ReplicaEndpoint.score)
        if max_staleness_ms is None:
            # degrade-before-shed: an unbounded read prefers a stale
            # answer (explicit x-pathway-stale headers) over a 503
            stale = [
                ep
                for ep in self.endpoints
                if ep.name not in tried and ep.serves_stale()
            ]
            return sorted(stale, key=ReplicaEndpoint.score)
        return []

    async def _attempt(
        self, ep: ReplicaEndpoint, request, body: bytes, deadline: float
    ) -> tuple[int, bytes, dict]:
        """One forwarded attempt; raises _Transport on a dead replica."""
        import aiohttp

        from pathway_tpu.observability import tracing

        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise asyncio.TimeoutError()
        headers = {
            k: request.headers[k] for k in _FWD_HEADERS if k in request.headers
        }
        headers["x-pathway-deadline-ms"] = f"{remaining * 1000.0:.1f}"
        span = tracing.get_tracer().span(
            "router.attempt", replica=ep.name
        )
        ep.inflight += 1
        t0 = time.perf_counter()
        try:
            with span:
                if span.context is not None:
                    headers["traceparent"] = span.context.traceparent()
                try:
                    async with self._session.post(
                        ep.url + request.path,
                        data=body,
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=remaining),
                    ) as resp:
                        payload = await resp.read()
                        out_headers = {
                            k: v
                            for k, v in resp.headers.items()
                            if k.lower() in _BACK_HEADERS
                        }
                        span.set_attribute("status", resp.status)
                        return resp.status, payload, out_headers
                except asyncio.TimeoutError:
                    span.set_attribute("status", "deadline")
                    raise
                except aiohttp.ClientError as e:
                    span.set_attribute("status", f"transport:{type(e).__name__}")
                    raise _Transport(f"{type(e).__name__}: {e}") from e
        finally:
            ep.inflight -= 1
            dt_ms = (time.perf_counter() - t0) * 1000.0
            ep.ewma_ms = 0.8 * ep.ewma_ms + 0.2 * dt_ms

    async def _handle(self, request):
        from aiohttp import web

        from pathway_tpu.observability import tracing

        body = await request.read()
        deadline = time.monotonic() + self._deadline_budget_s(request)
        max_st = self._max_staleness_ms(request)
        span = tracing.get_tracer().span(
            "router.request",
            parent=tracing.parse_traceparent(
                request.headers.get("traceparent")
            ),
            root=True,
            ingress=True,
            route=request.path,
        )
        with span:
            status, payload, headers, outcome, replica = (
                await self._route(request, body, deadline, max_st)
            )
            span.set_attribute("status", status)
            span.set_attribute("outcome", outcome)
        self._m_requests.labels(replica, outcome).inc()
        if span.context is not None:
            headers["traceparent"] = span.context.traceparent()
        # content type rides the passthrough headers (aiohttp rejects a
        # content_type argument when the header is already present)
        return web.Response(body=payload, status=status, headers=headers)

    async def _route(
        self, request, body: bytes, deadline: float, max_st: float | None
    ) -> tuple[int, bytes, dict, str, str]:
        tried: set[str] = set()
        last_shed: tuple[int, bytes, dict] | None = None
        failure_retries = 0
        while True:
            cands = self._candidates(max_st, tried)
            if not cands:
                break
            ep = cands[0]
            tried.add(ep.name)
            try:
                status, payload, headers = await self._attempt_hedged(
                    ep, cands[1:], tried, request, body, deadline
                )
            except asyncio.TimeoutError:
                # the ORIGINAL deadline is spent: no retry can help
                return (
                    504,
                    _json_err("deadline exceeded at router"),
                    {"content-type": "application/json"},
                    "deadline",
                    ep.name,
                )
            except _Transport as e:
                # dead replica: eject, fire listeners, retry a sibling
                # within the same deadline (never this one — `tried`).
                # Only FAILURES consume the bounded retry budget.
                self._eject(ep, f"transport: {e}")
                if failure_retries >= self.retries:
                    break
                failure_retries += 1
                self._m_retries.inc()
                continue
            if status in (429, 503):
                # shed (admission or staleness-bound): steer to a
                # sibling — bounded by the `tried` set, NOT by the
                # failure-retry budget, so an idle qualified replica is
                # always reached before a shed passes through
                last_shed = (status, payload, headers)
                continue
            outcome = "ok" if status == 200 else f"status_{status}"
            return status, payload, headers, outcome, ep.name
        if last_shed is not None:
            status, payload, headers = last_shed
            headers.setdefault("Retry-After", "1.0")
            return status, payload, headers, "shed", "none"
        # no replica qualifies at all: explicit 503 + Retry-After
        return (
            503,
            _json_err(
                "no replica qualifies"
                + (
                    f" within x-pathway-max-staleness-ms={max_st:g}"
                    if max_st is not None
                    else " (all ejected or unreachable)"
                )
            ),
            {
                "Retry-After": "1.0",
                "content-type": "application/json",
            },
            "no_replica",
            "none",
        )

    async def _attempt_hedged(
        self,
        primary: ReplicaEndpoint,
        alternates: list[ReplicaEndpoint],
        tried: set,
        request,
        body: bytes,
        deadline: float,
    ) -> tuple[int, bytes, dict]:
        """Primary attempt, plus a duplicate-suppressed hedge on a
        second replica when the primary is slower than the hedge
        budget.  Exactly one result is returned; the loser's task is
        cancelled."""
        if self.hedge_s <= 0 or not alternates:
            return await self._attempt(primary, request, body, deadline)
        task_a = asyncio.ensure_future(
            self._attempt(primary, request, body, deadline)
        )
        done, _pending = await asyncio.wait(
            {task_a}, timeout=self.hedge_s
        )
        if done:
            return task_a.result()  # fast path: no hedge fired
        hedge_ep = alternates[0]
        tried.add(hedge_ep.name)
        task_b = asyncio.ensure_future(
            self._attempt(hedge_ep, request, body, deadline)
        )
        pending = {task_a, task_b}
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    exc = t.exception()
                    if exc is None:
                        self._m_hedges.labels(
                            "primary" if t is task_a else "hedge"
                        ).inc()
                        return t.result()
                    # a transport-failed leg must STILL eject + fire
                    # failure listeners even when the other leg goes on
                    # to win — a dead primary masked by its hedge would
                    # otherwise keep its routing spot (inflight 0 beats
                    # every live sibling's score) until health polls
                    # catch up
                    if isinstance(exc, _Transport):
                        leg = primary if t is task_a else hedge_ep
                        self._eject(leg, f"transport: {exc}")
                # let the surviving leg decide; both failed → re-raise
                # the primary's error for normal retry handling
            task_a.result()  # raises
            raise _Transport("hedged attempts both failed")
        finally:
            for t in (task_a, task_b):
                if not t.done():
                    t.cancel()


def _json_err(msg: str) -> bytes:
    import json as _json

    return _json.dumps({"error": msg}).encode()
