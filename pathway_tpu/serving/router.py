"""Replica Shield failover router — deadline-aware, occupancy-weighted
balancing over the read replicas, IN FRONT of each replica's Surge Gate.

The router is a thin asyncio HTTP proxy holding no index state: it
forwards each read to the best-qualified replica and turns replica
failure into a retry instead of a client-visible error.

Routing policy (per request):

* **Qualify** — a replica is eligible when it is not ejected, reports
  ``ready`` (caught up with the writer since its current subscription —
  a restarted replica is only re-admitted once it clears this
  freshness bound) and, when the request carries
  ``x-pathway-max-staleness-ms``, its last reported staleness fits the
  bound.  The replica re-checks the bound locally at serve time, so a
  stale-between-polls replica answers 503 and the router moves on.
* **Degrade before shed** — when no replica is fresh but some are alive
  and the request did NOT bound staleness, the router serves from a
  stale replica (PR 8's stale-responder contract: explicit
  ``x-pathway-stale`` headers, never silent).  Explicit 503 +
  ``Retry-After`` goes out only when NO replica qualifies at all.
* **Pick** — occupancy-weighted: fewest in-flight (router-side counter
  + the replica's reported admission occupancy), EWMA latency as the
  tie-break.
* **Retry** — a transport failure (dead replica: connection refused /
  reset mid-response) ejects the replica, fires failure listeners
  (the HostMesh ``add_failure_listener`` contract), and retries the
  SAME request on a different replica within the ORIGINAL deadline —
  never the ejected one, at most ``PATHWAY_SERVING_RETRIES`` (default
  1) extra attempts.  Every attempt is a ``router.attempt`` child span,
  so the retry hop is visible in the stitched trace.
* **Hedge** — with ``PATHWAY_SERVING_HEDGE_MS`` set, a primary attempt
  that has not answered within the hedge budget gets a duplicate on a
  second replica; the first response wins and the loser is cancelled
  (duplicate-suppressed — reads are idempotent, exactly one response
  reaches the client).

Health: a background poller GETs every replica's ``/replica/health``
each ``PATHWAY_ROUTER_HEALTH_MS`` (heartbeat analog); consecutive
misses eject.  Ejected replicas keep being polled and re-admit only
once they report ``ready`` again.

Deadlines: ``x-pathway-deadline-ms`` propagates with the REMAINING
budget per attempt, so a retried request never outlives its original
deadline, and the trace context rides ``traceparent`` end to end.

Shard Harbor (scatter-gather): with a shard map (``shards=[[urls...],
...]`` or ``PATHWAY_SERVING_SHARD_MAP`` — ``|``-separated shards of
``,``-separated member URLs), each replica owns ONE jk-hash key range
of the corpus, and a read fans out to one qualified member per shard
(the same occupancy-weighted pick WITHIN the shard), merging the
per-shard top-k into the global top-k (:func:`merge_topk` — per-shard
key sets are disjoint, so the union of per-shard top-k always contains
the global top-k).  Per-shard attempts are ``router.attempt`` child
spans carrying a ``shard`` attribute.  Partial-shard outage follows
the established degrade ladder PER SHARD: fresh member first, stale
member for unbounded reads; when a shard has NOBODY to answer, the
whole read sheds with an explicit 503 + ``Retry-After`` NAMING the
missing shards (``x-pathway-missing-shards``) — a partial corpus is
never silently served as if it were complete.  A torn shard map
(empty shard, member listed in two shards) is rejected at construction
(:func:`validate_shard_map`), not discovered as wrong answers.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from typing import Any, Callable

_FWD_HEADERS = (
    # request headers forwarded to the replica verbatim
    "x-pathway-max-staleness-ms",
    "content-type",
    # Tenant Weave identity: replicas run their own tenant ledgers, so
    # the shed lands on the hot tenant at every member a request is
    # steered to
    "x-pathway-tenant",
    "x-pathway-tenant-class",
)
_BACK_HEADERS = (
    # response headers surfaced back to the client
    "x-pathway-replica",
    "x-pathway-applied-tick",
    "x-pathway-staleness-seconds",
    "x-pathway-stale",
    "retry-after",
    "content-type",
)


def replicas_from_env() -> list[str]:
    """PATHWAY_SERVING_REPLICAS: comma-separated replica base URLs
    (e.g. ``http://127.0.0.1:9101,http://127.0.0.1:9102``)."""
    raw = os.environ.get("PATHWAY_SERVING_REPLICAS", "")
    return [u.strip().rstrip("/") for u in raw.split(",") if u.strip()]


def shard_map_from_env() -> list[list[str]] | None:
    """PATHWAY_SERVING_SHARD_MAP: ``|``-separated shards (position =
    shard id) of ``,``-separated member URLs, e.g.
    ``http://h:9101,http://h:9102|http://h:9103,http://h:9104`` for a
    2-shard × 2-member plane.  None when unset."""
    raw = os.environ.get("PATHWAY_SERVING_SHARD_MAP", "")
    if not raw.strip():
        return None
    shards = [
        [u.strip().rstrip("/") for u in part.split(",") if u.strip()]
        for part in raw.split("|")
    ]
    validate_shard_map(shards)
    return shards


def validate_shard_map(shards: list[list[str]]) -> None:
    """Reject a torn shard assignment map at BOOT: every shard needs at
    least one member, and no member may appear in two shards (it would
    be fed two different key ranges and answer both wrongly)."""
    if not shards:
        raise ValueError("shard map is empty")
    seen: dict[str, int] = {}
    for s, members in enumerate(shards):
        if not members:
            raise ValueError(
                f"torn shard map: shard {s} has no members — every key "
                "range needs at least one owner"
            )
        for url in members:
            if url in seen:
                raise ValueError(
                    f"torn shard map: {url} is listed in shard "
                    f"{seen[url]} AND shard {s} — a member owns exactly "
                    "one key range"
                )
            seen[url] = s


def merge_topk(
    per_shard_matches: list[list], k: int
) -> list[list]:
    """Merge per-shard top-k ``[key, score]`` lists into the global
    top-k: shards own disjoint key ranges, so the union of per-shard
    top-k (each ≥ k deep or exhausted) always contains the global
    top-k.  Ordering is (score desc, key asc) — the deterministic
    tie-break that makes the merge bit-equal to an unsharded index
    using the same rule, regardless of how the corpus was split."""
    merged = [m for shard in per_shard_matches for m in shard]
    merged.sort(key=lambda m: (-float(m[1]), m[0]))
    return [list(m) for m in merged[: max(int(k), 0)]]


def hedge_ms_env() -> float:
    raw = os.environ.get("PATHWAY_SERVING_HEDGE_MS", "") or "0"
    try:
        return max(float(raw), 0.0)
    except ValueError:
        raise ValueError(
            f"PATHWAY_SERVING_HEDGE_MS={raw!r} is not a number"
        ) from None


class _Transport(Exception):
    """Replica transport failure (dead/unreachable) — retryable."""


class ReplicaEndpoint:
    """Router-side view of one replica: URL + health + occupancy."""

    def __init__(self, name: str, url: str, shard: int = 0):
        self.name = name
        self.url = url.rstrip("/")
        self.shard = shard  # the jk-hash key range this member owns
        self.inflight = 0  # router-side in-flight (attempts)
        self.reported_inflight = 0  # replica's admission occupancy
        self.ewma_ms = 0.0
        self.applied_tick = -1
        self.staleness_s: float | None = None
        self.ready = False
        self.alive = False  # last health poll answered
        self.ejected = False
        self.eject_reason = ""
        self.misses = 0

    def score(self) -> tuple:
        return (
            self.inflight + self.reported_inflight,
            self.ewma_ms,
            random.random(),
        )

    def qualifies(self, max_staleness_ms: float | None) -> bool:
        if self.ejected or not self.ready:
            return False
        if max_staleness_ms is None:
            return True
        s = self.staleness_s
        return s is not None and s * 1000.0 <= max_staleness_ms

    def serves_stale(self) -> bool:
        """Degraded tier: alive (answers health) but not fresh."""
        return self.alive and not self.ejected


class _WfqDispatch:
    """Tenant-fair dispatch window: at most ``width`` requests route
    concurrently, and when the window is full, waiters release in
    TenantLedger WFQ virtual-finish order — a cold tenant's first
    request jumps ahead of a hot tenant's backlog instead of FIFO-ing
    behind it.  Runs entirely on the router's event loop (no locks);
    exists only when the ledger is armed, so the ``PATHWAY_TENANT_QOS``
    unset path stays byte-identical."""

    def __init__(self, ledger, width: int):
        self.ledger = ledger
        self.width = max(int(width), 1)
        self._inflight = 0
        self._waiters: list[tuple[float, int, Any]] = []  # (tag, seq, fut)
        self._seq = 0

    @property
    def queued(self) -> int:
        return len(self._waiters)

    async def acquire(self, tenant: str | None, tenant_class: str | None):
        """Charge the tenant's WFQ clock and wait for a dispatch slot.
        Returns (tag, waited) — ``waited`` is True when the request
        actually queued behind the window."""
        import heapq

        # charge_only: the dispatch window orders, it never sheds —
        # admission-control sheds stay the replicas' ladder's job
        tag = self.ledger.admit(
            tenant or "", tenant_class, pressure=False, charge_only=True
        )
        if self._inflight < self.width and not self._waiters:
            self._inflight += 1
            self.ledger.note_dispatched((tag,))
            return tag, False
        fut = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self._waiters, (tag, self._seq, fut))
        await fut
        return tag, True

    def release(self) -> None:
        import heapq

        self._inflight -= 1
        while self._waiters and self._inflight < self.width:
            tag, _seq, fut = heapq.heappop(self._waiters)
            if fut.cancelled():
                continue
            self._inflight += 1
            self.ledger.note_dispatched((tag,))
            fut.set_result(tag)


class FailoverRouter:
    def __init__(
        self,
        replicas: list[str] | None = None,
        *,
        shards: list[list[str]] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        retries: int | None = None,
        hedge_ms: float | None = None,
        health_interval_ms: float | None = None,
        liveness_misses: int = 3,
        default_deadline_ms: float = 30_000.0,
        max_deadline_ms: float = 120_000.0,
        cache: Any = None,
    ):
        if shards is None and replicas is None:
            shards = shard_map_from_env()
        if shards is not None:
            validate_shard_map(shards)
            self.n_shards = len(shards)
            self.endpoints = [
                ReplicaEndpoint(f"s{s}.replica{i}", u, shard=s)
                for s, members in enumerate(shards)
                for i, u in enumerate(members)
            ]
        else:
            urls = replicas if replicas is not None else replicas_from_env()
            if not urls:
                raise ValueError(
                    "FailoverRouter needs at least one replica URL (pass "
                    "replicas=[...] / shards=[[...]], or set "
                    "PATHWAY_SERVING_REPLICAS / PATHWAY_SERVING_SHARD_MAP)"
                )
            self.n_shards = 1
            self.endpoints = [
                ReplicaEndpoint(f"replica{i}", u) for i, u in enumerate(urls)
            ]
        self.host = host
        self.port = port
        if retries is None:
            retries = int(os.environ.get("PATHWAY_SERVING_RETRIES", "1") or 1)
        self.retries = max(int(retries), 0)
        self.hedge_s = (
            hedge_ms_env() if hedge_ms is None else max(float(hedge_ms), 0.0)
        ) / 1000.0
        if health_interval_ms is None:
            health_interval_ms = float(
                os.environ.get("PATHWAY_ROUTER_HEALTH_MS", "250") or 250
            )
        self.health_interval_s = max(health_interval_ms, 20.0) / 1000.0
        self.liveness_misses = max(int(liveness_misses), 1)
        self.default_deadline_ms = float(default_deadline_ms)
        self.max_deadline_ms = float(max_deadline_ms)
        # Tenant Weave result cache (serving/result_cache.py): answer
        # repeat reads without a replica hop, invalidated precisely by
        # the writer's delta stream.  None (PATHWAY_ROUTER_CACHE unset)
        # keeps the request path byte-identical to the cache-less plane.
        if cache is None:
            from pathway_tpu.serving.result_cache import cache_from_env

            cache = cache_from_env()
        self.cache = cache
        self._lock = threading.Lock()
        self._failure_listeners: list[Callable[[str, str], None]] = []
        self._past_failures: list[tuple[str, str]] = []
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_ready = threading.Event()
        self._bound = threading.Event()
        self._stop_async: Any = None
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopped = False
        from pathway_tpu.observability import REGISTRY

        self._m_requests = REGISTRY.counter(
            "pathway_router_requests_total",
            "routed read requests, by chosen replica and outcome "
            "(ok / shed / stale_shed / error / no_replica)",
            labelnames=("replica", "outcome"),
        )
        self._m_retries = REGISTRY.counter(
            "pathway_router_retries_total",
            "same-deadline retries after a replica failed mid-request",
        )
        self._m_hedges = REGISTRY.counter(
            "pathway_router_hedges_total",
            "hedged duplicates fired after PATHWAY_SERVING_HEDGE_MS, by "
            "which attempt won",
            labelnames=("winner",),
        )
        self._m_ejections = REGISTRY.counter(
            "pathway_router_ejections_total",
            "replica ejections, by replica and reason",
            labelnames=("replica", "reason"),
        )
        self._m_inflight = REGISTRY.gauge(
            "pathway_router_replica_inflight",
            "router-side in-flight attempts per replica",
            labelnames=("replica",),
        )
        self._gauge_names: set[str] = set()
        for ep in self.endpoints:
            self._gauge_names.add(ep.name)
            self._m_inflight.labels(ep.name).set_function(
                lambda ep=ep: ep.inflight
            )
        # Tenant-aware dispatch: with the tenant ledger armed
        # (PATHWAY_TENANT_QOS=1) the router's dispatch window releases
        # waiting requests in WFQ virtual-finish order instead of FIFO.
        # A None ledger keeps the request path byte-identical.
        from pathway_tpu.serving.tenancy import ledger_for

        self.tenant_ledger = ledger_for(None, route="router")
        self._dispatch: _WfqDispatch | None = None
        if self.tenant_ledger is not None:
            width = int(
                os.environ.get("PATHWAY_ROUTER_DISPATCH_WINDOW", "8") or 8
            )
            self._dispatch = _WfqDispatch(self.tenant_ledger, width)
            self._m_dispatch_waits = REGISTRY.counter(
                "pathway_router_dispatch_waits_total",
                "requests that queued behind the tenant-fair dispatch "
                "window before routing",
            )
            REGISTRY.gauge(
                "pathway_router_dispatch_queued",
                "requests currently queued in the tenant-fair dispatch "
                "window",
            ).set_function(lambda d=self._dispatch: d.queued)

    # --- failure listeners (HostMesh contract) ----------------------------

    def add_failure_listener(self, fn: Callable[[str, str], None]) -> None:
        """``fn(replica_name, reason)`` fires at ejection; late
        registrants replay past ejections (mesh parity)."""
        with self._lock:
            self._failure_listeners.append(fn)
            past = list(self._past_failures)
        for name, reason in past:
            try:
                fn(name, reason)
            except Exception:
                pass

    def _eject(self, ep: ReplicaEndpoint, reason: str) -> None:
        with self._lock:
            if ep.ejected:
                return
            ep.ejected = True
            ep.ready = False
            ep.eject_reason = reason
            listeners = list(self._failure_listeners)
            self._past_failures.append((ep.name, reason))
        self._m_ejections.labels(ep.name, reason.split(":")[0]).inc()
        import logging

        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            "router-eject",
            reason,
            persist=True,
            replica=ep.name,
            shard=ep.shard,
        )
        logging.getLogger("pathway_tpu").warning(
            "router: ejected %s (%s)", ep.name, reason
        )
        for fn in listeners:
            try:
                fn(ep.name, reason)
            except Exception:
                pass

    def _readmit(self, ep: ReplicaEndpoint) -> None:
        with self._lock:
            if not ep.ejected:
                return
            ep.ejected = False
            ep.eject_reason = ""
        import logging

        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            "router-readmit",
            f"fresh at tick {ep.applied_tick}",
            tick=ep.applied_tick,
            replica=ep.name,
            shard=ep.shard,
        )
        logging.getLogger("pathway_tpu").info(
            "router: re-admitted %s (fresh at tick %d)",
            ep.name,
            ep.applied_tick,
        )

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "FailoverRouter":
        if self._started:
            return self
        self._started = True
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="pw-router"
        )
        self._thread.start()
        self._bound.wait(30.0)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started or self._stopped:
            return
        self._stopped = True
        if self.cache is not None:
            self.cache.close()
        self._loop_ready.wait(timeout)
        stop_async = self._stop_async
        if stop_async is not None:
            try:
                stop_async()
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout)

    def _run(self) -> None:
        import aiohttp
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        stop_ev = asyncio.Event()
        self._stop_async = lambda: loop.call_soon_threadsafe(stop_ev.set)
        self._loop_ready.set()

        async def main():
            self._session = aiohttp.ClientSession()
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.port = (
                runner.addresses[0][1] if runner.addresses else self.port
            )
            self._bound.set()
            poller = asyncio.ensure_future(self._health_loop())
            if not self._stopped:
                await stop_ev.wait()
            poller.cancel()
            await self._session.close()
            await runner.cleanup()

        try:
            loop.run_until_complete(main())
        finally:
            self._bound.set()
            loop.close()

    # --- health -----------------------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            for ep in list(self.endpoints):
                await self._poll_one(ep)
            await asyncio.sleep(self.health_interval_s)

    async def _poll_one(self, ep: ReplicaEndpoint) -> None:
        """One health probe of one endpoint (the poll loop's body; the
        shard-map swap primes NEW endpoints through it too)."""
        import aiohttp

        try:
            async with self._session.get(
                ep.url + "/replica/health",
                timeout=aiohttp.ClientTimeout(total=1.0),
            ) as resp:
                h = await resp.json()
            ep.alive = True
            ep.misses = 0
            ep.applied_tick = int(h.get("applied_tick", -1))
            s = h.get("staleness_seconds")
            ep.staleness_s = None if s is None else float(s)
            ep.reported_inflight = int(h.get("inflight", 0))
            ep.ready = bool(h.get("ready", False))
            # Shard Harbor: a member whose REPORTED ownership
            # disagrees with its slot in the map would serve the
            # wrong key range with healthy-looking 200s —
            # merged top-k silently drops its slot's range (and
            # duplicates another's).  The health payload names
            # what the member actually owns; trust it over the
            # map and refuse to route there.
            mismatch = None
            try:
                rep_shard = int(h.get("shard", -1))
                rep_n = int(h.get("n_shards", 0))
            except (TypeError, ValueError):
                rep_shard, rep_n = -1, 0
            if self.n_shards > 1:
                if rep_n > 0 and rep_n != self.n_shards:
                    mismatch = (
                        f"shard-mismatch: member splits the "
                        f"corpus {rep_n} way(s), the map has "
                        f"{self.n_shards}"
                    )
                elif rep_shard >= 0 and rep_shard != ep.shard:
                    mismatch = (
                        f"shard-mismatch: member owns shard "
                        f"{rep_shard}, the map lists it under "
                        f"shard {ep.shard}"
                    )
            elif rep_n > 1:
                # the inverse misconfig: a shard-owning member
                # behind a PLAIN replicas-list router would
                # answer every routed read from 1/S of the
                # corpus with healthy-looking 200s
                mismatch = (
                    f"shard-mismatch: member owns 1/{rep_n} of "
                    "the corpus but this router is unsharded "
                    "(use PATHWAY_SERVING_SHARD_MAP)"
                )
            if mismatch is not None:
                ep.ready = False
                if not ep.ejected:
                    self._eject(ep, mismatch)
            elif ep.ejected and ep.ready:
                # the freshness bound for re-admission: the
                # replica reports caught-up again (and, on a
                # sharded plane, its ownership matches its slot)
                self._readmit(ep)
        except asyncio.CancelledError:
            raise
        except Exception:
            ep.misses += 1
            ep.alive = False
            ep.ready = False
            if ep.misses >= self.liveness_misses and not ep.ejected:
                self._eject(
                    ep,
                    f"liveness: {ep.misses} consecutive health "
                    "probes failed",
                )

    # --- live shard-map swap (Shard Flux) ---------------------------------

    def swap_shard_map(
        self, shards: list[list[str]], timeout: float = 30.0
    ) -> None:
        """Atomically swap the routing topology to a NEW shard map at
        the reshard commit barrier.  The new map is validated like the
        boot map; members already routed to (same URL) keep their live
        health state; brand-new members get one immediate health probe
        before the swap so the plane does not eat a
        health-interval-long 503 window.  In-flight requests finish
        against the map they started on; every request after the swap
        sees only the new one — there is no in-between state."""
        validate_shard_map(shards)
        if not self._started:
            # boot-time configuration: no loop to defer to
            self._install_map(shards, [])
            return
        self._loop_ready.wait(timeout)
        loop = self._loop
        if loop is None:
            raise RuntimeError("router loop never started")
        fut = asyncio.run_coroutine_threadsafe(
            self._swap_async(shards), loop
        )
        fut.result(timeout)

    def _install_map(
        self, shards: list[list[str]], primed: list[ReplicaEndpoint]
    ) -> None:
        by_url = {ep.url: ep for ep in self.endpoints}
        primed_by_url = {ep.url: ep for ep in primed}
        new_eps: list[ReplicaEndpoint] = []
        for s, members in enumerate(shards):
            for i, u in enumerate(members):
                url = u.rstrip("/")
                ep = primed_by_url.get(url) or by_url.get(url)
                if ep is not None:
                    ep.name = f"s{s}.replica{i}"
                    ep.shard = s
                else:
                    ep = ReplicaEndpoint(f"s{s}.replica{i}", url, shard=s)
                new_eps.append(ep)
        with self._lock:
            self.endpoints = new_eps
            self.n_shards = len(shards)
        for ep in new_eps:
            # (re)bind: set_function REPLACES, so a reused label never
            # double-reports and reshard churn never accumulates
            # closures pinning dead ReplicaEndpoint objects
            self._gauge_names.add(ep.name)
            self._m_inflight.labels(ep.name).set_function(
                lambda ep=ep: ep.inflight
            )
        live = {ep.name for ep in new_eps}
        for name in self._gauge_names - live:
            # cardinality bound: a retired replica's series is REMOVED,
            # not zeroed forever — reshard churn must not grow the
            # exposition without bound (one series per name that ever
            # existed)
            self._m_inflight.remove(name)
        self._gauge_names &= live
        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            "shard-swap",
            f"{len(shards)} shard(s) x "
            f"{'/'.join(str(len(m)) for m in shards)} member(s)",
            persist=True,
            n_shards=len(shards),
            members=[len(m) for m in shards],
        )

    async def _swap_async(self, shards: list[list[str]]) -> None:
        known = {ep.url for ep in self.endpoints}
        primed: list[ReplicaEndpoint] = []
        for s, members in enumerate(shards):
            for i, u in enumerate(members):
                url = u.rstrip("/")
                if url in known:
                    continue
                ep = ReplicaEndpoint(f"s{s}.replica{i}", url, shard=s)
                await self._poll_one(ep)
                primed.append(ep)
        self._install_map(shards, primed)
        import logging

        logging.getLogger("pathway_tpu").info(
            "router: swapped shard map to %d shard(s) x %s member(s)",
            len(shards),
            "/".join(str(len(m)) for m in shards),
        )

    # --- request path -----------------------------------------------------

    def _deadline_budget_s(self, request) -> float:
        import math

        raw = request.headers.get("x-pathway-deadline-ms")
        budget_ms = None
        if raw is not None:
            try:
                budget_ms = float(raw)
            except ValueError:
                budget_ms = None
            if budget_ms is not None and not math.isfinite(budget_ms):
                budget_ms = None
        if budget_ms is None:
            budget_ms = self.default_deadline_ms
        return min(budget_ms, self.max_deadline_ms) / 1000.0

    @staticmethod
    def _max_staleness_ms(request) -> float | None:
        import math

        raw = request.headers.get("x-pathway-max-staleness-ms")
        if raw is None:
            return None
        try:
            v = float(raw)
        except ValueError:
            return None
        return v if math.isfinite(v) else None

    def _candidates(
        self,
        max_staleness_ms: float | None,
        tried: set,
        shard: int | None = None,
    ) -> list[ReplicaEndpoint]:
        pool = (
            self.endpoints
            if shard is None
            else [ep for ep in self.endpoints if ep.shard == shard]
        )
        fresh = [
            ep
            for ep in pool
            if ep.name not in tried and ep.qualifies(max_staleness_ms)
        ]
        if fresh:
            return sorted(fresh, key=ReplicaEndpoint.score)
        if max_staleness_ms is None:
            # degrade-before-shed: an unbounded read prefers a stale
            # answer (explicit x-pathway-stale headers) over a 503
            stale = [
                ep
                for ep in pool
                if ep.name not in tried and ep.serves_stale()
            ]
            return sorted(stale, key=ReplicaEndpoint.score)
        return []

    async def _attempt(
        self, ep: ReplicaEndpoint, request, body: bytes, deadline: float
    ) -> tuple[int, bytes, dict]:
        """One forwarded attempt; raises _Transport on a dead replica."""
        import aiohttp

        from pathway_tpu.observability import tracing

        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise asyncio.TimeoutError()
        headers = {
            k: request.headers[k] for k in _FWD_HEADERS if k in request.headers
        }
        headers["x-pathway-deadline-ms"] = f"{remaining * 1000.0:.1f}"
        span = tracing.get_tracer().span(
            "router.attempt", replica=ep.name, shard=str(ep.shard)
        )
        ep.inflight += 1
        t0 = time.perf_counter()
        try:
            with span:
                if span.context is not None:
                    headers["traceparent"] = span.context.traceparent()
                try:
                    async with self._session.post(
                        ep.url + request.path,
                        data=body,
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=remaining),
                    ) as resp:
                        payload = await resp.read()
                        out_headers = {
                            k: v
                            for k, v in resp.headers.items()
                            if k.lower() in _BACK_HEADERS
                        }
                        span.set_attribute("status", resp.status)
                        return resp.status, payload, out_headers
                except asyncio.TimeoutError:
                    span.set_attribute("status", "deadline")
                    raise
                except aiohttp.ClientError as e:
                    span.set_attribute("status", f"transport:{type(e).__name__}")
                    raise _Transport(f"{type(e).__name__}: {e}") from e
        finally:
            ep.inflight -= 1
            dt_ms = (time.perf_counter() - t0) * 1000.0
            ep.ewma_ms = 0.8 * ep.ewma_ms + 0.2 * dt_ms

    # --- Fleet Lens federation --------------------------------------------

    async def _fleet_get(self, request):
        """One observability plane for the whole mesh: the router is the
        process that already knows every member's base URL, so it
        federates their `/metrics`, `/debug/events` and `/debug/trace`
        into member-labeled fleet views.  The blocking urllib fetches
        run on the default executor — the proxy loop keeps serving."""
        from aiohttp import web

        route = request.path
        members = [(ep.name, ep.url) for ep in self.endpoints]
        loop = asyncio.get_event_loop()
        if route == "/debug/events":
            from pathway_tpu.observability.journal import journal

            j = journal()
            return web.json_response(
                {"member": j.member, "events": j.events()}
            )
        if route == "/fleet/metrics":
            from pathway_tpu.observability import REGISTRY
            from pathway_tpu.observability.fleet import federate_metrics

            local = ("router", REGISTRY.render())
            text, errors = await loop.run_in_executor(
                None, lambda: federate_metrics(members, local=local)
            )
            headers = (
                {"x-pathway-fleet-errors": str(len(errors))}
                if errors
                else {}
            )
            return web.Response(
                text=text, content_type="text/plain", headers=headers
            )
        if route == "/fleet/events":
            from pathway_tpu.observability.fleet import federate_events
            from pathway_tpu.observability.journal import journal

            local = journal().events()
            merged = await loop.run_in_executor(
                None, lambda: federate_events(members, local=local)
            )
            return web.json_response(merged)
        # /fleet/trace
        from pathway_tpu.observability.fleet import stitch_traces
        from pathway_tpu.observability.tracing import get_tracer

        trace_id = request.query.get("trace_id") or None
        local = ("router", get_tracer().chrome_trace())
        data = await loop.run_in_executor(
            None,
            lambda: stitch_traces(members, trace_id=trace_id, local=local),
        )
        return web.json_response(data)

    async def _handle(self, request):
        from aiohttp import web

        from pathway_tpu.observability import tracing

        if request.method == "GET" and request.path in (
            "/fleet/metrics",
            "/fleet/events",
            "/fleet/trace",
            "/debug/events",
        ):
            return await self._fleet_get(request)
        body = await request.read()
        deadline = time.monotonic() + self._deadline_budget_s(request)
        max_st = self._max_staleness_ms(request)
        tenant = request.headers.get("x-pathway-tenant")
        span = tracing.get_tracer().span(
            "router.request",
            parent=tracing.parse_traceparent(
                request.headers.get("traceparent")
            ),
            root=True,
            ingress=True,
            route=request.path,
        )
        with span:
            hit = (
                self.cache.lookup(tenant, body, max_st, path=request.path)
                if self.cache is not None and request.method == "POST"
                else None
            )
            if hit is not None:
                # answered with ZERO replica hops; the hit headers
                # carry the degrade contract (applied tick + the
                # invalidation stream's staleness) plus x-pathway-cache
                status, payload, headers = hit
                span.set_attribute("status", status)
                span.set_attribute("outcome", "cache_hit")
                self._m_requests.labels("cache", "cache_hit").inc()
                if span.context is not None:
                    headers["traceparent"] = span.context.traceparent()
                return web.Response(
                    body=payload, status=status, headers=headers
                )
            from pathway_tpu.generate.serving import is_generate_route

            if self._dispatch is not None:
                from pathway_tpu.serving.tenancy import TENANT_CLASS_HEADER

                _tag, waited = await self._dispatch.acquire(
                    tenant, request.headers.get(TENANT_CLASS_HEADER)
                )
                if waited:
                    self._m_dispatch_waits.inc()
            try:
                if self.n_shards > 1 and not is_generate_route(request.path):
                    status, payload, headers, outcome, replica = (
                        await self._route_scatter(
                            request, body, deadline, max_st
                        )
                    )
                else:
                    # /generate rides the same occupancy/staleness/
                    # tenant single-member ladder even on a sharded
                    # plane: generation is stateful on the member
                    # holding the KV pages — scatter-gather is a
                    # retrieval concept
                    status, payload, headers, outcome, replica = (
                        await self._route(request, body, deadline, max_st)
                    )
            finally:
                if self._dispatch is not None:
                    self._dispatch.release()
            span.set_attribute("status", status)
            span.set_attribute("outcome", outcome)
            if self.cache is not None and request.method == "POST":
                self.cache.store(
                    tenant,
                    body,
                    max_st,
                    status,
                    payload,
                    headers,
                    path=request.path,
                )
        self._m_requests.labels(replica, outcome).inc()
        if span.context is not None:
            headers["traceparent"] = span.context.traceparent()
        # content type rides the passthrough headers (aiohttp rejects a
        # content_type argument when the header is already present)
        return web.Response(body=payload, status=status, headers=headers)

    async def _route(
        self, request, body: bytes, deadline: float, max_st: float | None
    ) -> tuple[int, bytes, dict, str, str]:
        tried: set[str] = set()
        last_shed: tuple[int, bytes, dict] | None = None
        failure_retries = 0
        while True:
            cands = self._candidates(max_st, tried)
            if not cands:
                break
            ep = cands[0]
            tried.add(ep.name)
            try:
                status, payload, headers = await self._attempt_hedged(
                    ep, cands[1:], tried, request, body, deadline
                )
            except asyncio.TimeoutError:
                # the ORIGINAL deadline is spent: no retry can help
                return (
                    504,
                    _json_err("deadline exceeded at router"),
                    {"content-type": "application/json"},
                    "deadline",
                    ep.name,
                )
            except _Transport as e:
                # dead replica: eject, fire listeners, retry a sibling
                # within the same deadline (never this one — `tried`).
                # Only FAILURES consume the bounded retry budget.
                self._eject(ep, f"transport: {e}")
                if failure_retries >= self.retries:
                    break
                failure_retries += 1
                self._m_retries.inc()
                continue
            if status in (429, 503):
                # shed (admission or staleness-bound): steer to a
                # sibling — bounded by the `tried` set, NOT by the
                # failure-retry budget, so an idle qualified replica is
                # always reached before a shed passes through
                last_shed = (status, payload, headers)
                continue
            outcome = "ok" if status == 200 else f"status_{status}"
            return status, payload, headers, outcome, ep.name
        if last_shed is not None:
            status, payload, headers = last_shed
            headers.setdefault("Retry-After", "1.0")
            return status, payload, headers, "shed", "none"
        # no replica qualifies at all: explicit 503 + Retry-After
        return (
            503,
            _json_err(
                "no replica qualifies"
                + (
                    f" within x-pathway-max-staleness-ms={max_st:g}"
                    if max_st is not None
                    else " (all ejected or unreachable)"
                )
            ),
            {
                "Retry-After": "1.0",
                "content-type": "application/json",
            },
            "no_replica",
            "none",
        )

    # --- scatter-gather (Shard Harbor) ------------------------------------

    @staticmethod
    def _request_k(body: bytes) -> int:
        import json as _json

        try:
            v = _json.loads(body or b"{}")
            return max(int(v.get("k", 3)), 0)
        except (ValueError, TypeError, AttributeError):
            return 3

    async def _shard_fetch(
        self,
        shard: int,
        request,
        body: bytes,
        deadline: float,
        max_st: float | None,
    ):
        """One shard's leg of the scatter: same qualify/degrade/retry
        ladder as the single-shard route, restricted to the shard's
        members.  Returns (status, payload, headers, replica) on an
        answer, None when the shard is unavailable (every member tried,
        ejected, or over the staleness bound)."""
        tried: set[str] = set()
        failure_retries = 0
        while True:
            cands = self._candidates(max_st, tried, shard=shard)
            if not cands:
                return None
            ep = cands[0]
            tried.add(ep.name)
            try:
                status, payload, headers = await self._attempt_hedged(
                    ep, cands[1:], tried, request, body, deadline
                )
            except asyncio.TimeoutError:
                raise  # the ORIGINAL deadline is spent: the gather
                # surfaces one 504 for the whole read
            except _Transport as e:
                self._eject(ep, f"transport: {e}")
                if failure_retries >= self.retries:
                    return None
                failure_retries += 1
                self._m_retries.inc()
                continue
            if status in (429, 503) or status >= 500:
                # shed or member error: steer to a shard sibling —
                # bounded by the tried set
                continue
            # 200 AND non-shed client errors (400/404/...) return: a
            # permanently-bad request must surface as its real status,
            # not burn every member and masquerade as a health outage
            return status, payload, headers, ep.name

    async def _route_scatter(
        self, request, body: bytes, deadline: float, max_st: float | None
    ) -> tuple[int, bytes, dict, str, str]:
        """Fan the read out to one qualified member per shard and merge
        per-shard top-k into global top-k.  Missing shards are NAMED
        (503 + Retry-After + x-pathway-missing-shards) — a partial
        corpus never masquerades as the whole one."""
        import json as _json

        k = self._request_k(body)
        # return_exceptions: every per-shard task runs to completion —
        # a bare gather would propagate the first TimeoutError and
        # leave the other shards' fetches running as orphans, retrying
        # members against a spent deadline after the 504 already went
        # out
        results = await asyncio.gather(
            *(
                self._shard_fetch(s, request, body, deadline, max_st)
                for s in range(self.n_shards)
            ),
            return_exceptions=True,
        )
        for r in results:
            if isinstance(r, BaseException) and not isinstance(
                r, asyncio.TimeoutError
            ):
                raise r
        if any(isinstance(r, asyncio.TimeoutError) for r in results):
            return (
                504,
                _json_err("deadline exceeded at router"),
                {"content-type": "application/json"},
                "deadline",
                "scatter",
            )
        missing = [s for s, r in enumerate(results) if r is None]
        if missing:
            names = ",".join(str(s) for s in missing)
            return (
                503,
                _json_err(
                    f"shard(s) {names} unavailable"
                    + (
                        f" within x-pathway-max-staleness-ms={max_st:g}"
                        if max_st is not None
                        else " (all members ejected or unreachable)"
                    )
                ),
                {
                    "Retry-After": "1.0",
                    "x-pathway-missing-shards": names,
                    "content-type": "application/json",
                },
                "shard_unavailable",
                "scatter",
            )
        per_shard = []
        applied_ticks: list[int] = []
        staleness: list[float] = []
        any_stale = False
        replicas = []
        for status, payload, headers, replica in results:
            if status != 200:
                # a client error (400/404/...) from any shard: the
                # request itself is bad — surface it unmerged
                return (
                    status,
                    payload,
                    headers,
                    f"status_{status}",
                    replica,
                )
            replicas.append(replica)
            try:
                matches = _json.loads(payload).get("matches", [])
            except ValueError:
                matches = None
            if matches is None:
                return (
                    502,
                    _json_err(
                        f"replica {replica} returned a non-KNN payload "
                        "on a sharded plane (scatter-gather needs the "
                        "matches contract)"
                    ),
                    {"content-type": "application/json"},
                    "bad_shard_payload",
                    replica,
                )
            per_shard.append(matches)
            tick = headers.get("x-pathway-applied-tick")
            if tick is not None:
                try:
                    applied_ticks.append(int(tick))
                except ValueError:
                    pass
            st = headers.get("x-pathway-staleness-seconds")
            if st is not None:
                try:
                    staleness.append(float(st))
                except ValueError:
                    pass
            if headers.get("x-pathway-stale"):
                any_stale = True
        merged = merge_topk(per_shard, k)
        out_headers = {
            "content-type": "application/json",
            "x-pathway-shards": str(self.n_shards),
            "x-pathway-replica": ",".join(replicas),
        }
        if applied_ticks:
            # the plane is only as fresh as its LEAST caught-up shard
            out_headers["x-pathway-applied-tick"] = str(min(applied_ticks))
        if staleness:
            out_headers["x-pathway-staleness-seconds"] = (
                f"{max(staleness):.3f}"
            )
        if any_stale:
            out_headers["x-pathway-stale"] = "true"
        return (
            200,
            _json.dumps({"matches": merged}).encode(),
            out_headers,
            "ok",
            "scatter",
        )

    async def _attempt_hedged(
        self,
        primary: ReplicaEndpoint,
        alternates: list[ReplicaEndpoint],
        tried: set,
        request,
        body: bytes,
        deadline: float,
    ) -> tuple[int, bytes, dict]:
        """Primary attempt, plus a duplicate-suppressed hedge on a
        second replica when the primary is slower than the hedge
        budget.  Exactly one result is returned; the loser's task is
        cancelled."""
        if self.hedge_s <= 0 or not alternates:
            return await self._attempt(primary, request, body, deadline)
        task_a = asyncio.ensure_future(
            self._attempt(primary, request, body, deadline)
        )
        done, _pending = await asyncio.wait(
            {task_a}, timeout=self.hedge_s
        )
        if done:
            return task_a.result()  # fast path: no hedge fired
        hedge_ep = alternates[0]
        tried.add(hedge_ep.name)
        task_b = asyncio.ensure_future(
            self._attempt(hedge_ep, request, body, deadline)
        )
        pending = {task_a, task_b}
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    exc = t.exception()
                    if exc is None:
                        self._m_hedges.labels(
                            "primary" if t is task_a else "hedge"
                        ).inc()
                        return t.result()
                    # a transport-failed leg must STILL eject + fire
                    # failure listeners even when the other leg goes on
                    # to win — a dead primary masked by its hedge would
                    # otherwise keep its routing spot (inflight 0 beats
                    # every live sibling's score) until health polls
                    # catch up
                    if isinstance(exc, _Transport):
                        leg = primary if t is task_a else hedge_ep
                        self._eject(leg, f"transport: {exc}")
                # let the surviving leg decide; both failed → re-raise
                # the primary's error for normal retry handling
            task_a.result()  # raises
            raise _Transport("hedged attempts both failed")
        finally:
            for t in (task_a, task_b):
                if not t.done():
                    t.cancel()


def _json_err(msg: str) -> bytes:
    import json as _json

    return _json.dumps({"error": msg}).encode()
