"""Tenant Weave hot-tenant result cache — answer repeat reads on the
router without touching a replica, invalidated PRECISELY by the
replication delta stream.

`serve_chaos` models a zipf tenant population: a handful of hot tenants
repeat the same handful of queries, and every repeat pays a full
router→replica hop even when nothing the query reads has changed.  The
PR-10 delta stream already names exactly which corpus keys changed each
tick (the writer publishes CONSOLIDATED per-tick deltas), which is
precisely the signal a correct result cache needs — so the cache
subscribes a :class:`~pathway_tpu.parallel.replicate.DeltaStreamClient`
(shard ``-1`` = the full corpus, so one subscription covers a sharded
plane too) and evicts per key instead of guessing with TTLs.

**Keying.**  ``(tenant, route path, query fingerprint, k, staleness
bound)`` — the fingerprint is the canonical JSON of the request body,
so two tenants never share an entry (isolation is part of the QoS
story), the same body POSTed to a different route never hits another
route's answer, and a bounded read never answers from an entry stored
under a different bound.

**Precise invalidation.**  A cached entry holds the KNN contract's
result set (``matches: [[key, score], ...]``), the set of keys it
contains, its worst kept score, and the (normalized) query vector.  One
tick's consolidated deltas evict exactly the entries whose result sets
could contain the changed keys:

* a **deleted** key evicts the entries whose result set contains it
  (removing a non-member only removes competition below the k-th match
  — survivors are untouched);
* an **upserted** key evicts the entries that contain it (the doc's
  vector changed, so its score did), the entries whose result set is
  not full (any new doc joins an under-filled top-k), and the entries
  whose query scores the new vector at or above their worst kept match
  (it would enter the top-k).  Everything else provably keeps the exact
  answer a fresh replica would give, so it survives.

On a sharded plane the same rule applies per key — an entry's shard
coverage is exactly the shard set of its result keys for deletions,
and an upsert in ANY shard is score-tested (a new doc from an uncovered
shard can still beat the worst kept match in the merged top-k).

Invalidation is SUBLINEAR in the cache size: containment evictions
come from the per-key reverse index, and the would-enter test selects
its candidates from a sorted worst-kept-score bound index (by
Cauchy-Schwarz, a doc of norm ``|d|`` can only enter an entry whose
``(worst - slack)/|q| <= |d|``) — a changed key tests a bound instead
of re-scoring every cached entry.  The bound is sharp for the ``dot``
metric (doc norms vary); under ``cosine`` both sides are normalized so
it degenerates to ~1 and the index selects nearly everything — the
eviction SET is identical to the full scan either way (property-tested
in tests/test_result_cache.py).

**Freshness contract (the PR-8 degrade headers hold through the
cache).**  A hit carries ``x-pathway-cache: hit`` plus
``x-pathway-applied-tick`` (the invalidation stream's applied tick —
the entry is guaranteed equal to a fresh answer as of that tick) and
``x-pathway-staleness-seconds`` (the stream's staleness clock).  When
the stream lags past ``PATHWAY_ROUTER_CACHE_MAX_LAG_MS`` (or past the
request's own ``x-pathway-max-staleness-ms``) the cache is BYPASSED —
a lagging invalidation feed must degrade to replica hops, never to
silently stale hits.  Writer death → standby takeover bumps the writer
incarnation, and the cache flushes wholesale on the bump (the new
writer's history may not extend the old one's); a ring resync does the
same.  Entries are only stored when the stream has NOT advanced past
the answering replica's applied tick — otherwise a delta the cache
already processed (but the replica had not applied when answering)
could never evict the entry.

Without a delta stream attached, the cache degrades to TIME-based
staleness only (``PATHWAY_ROUTER_CACHE_TTL_MS``) — the Graph Doctor's
``tenant-fairness`` rule flags this configuration, because a TTL can
serve an answer up to a full TTL staler than the corpus.

Escape hatch is total: with ``PATHWAY_ROUTER_CACHE`` unset (or 0) no
cache object is built and the router request path is byte-identical to
the pre-cache plane.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any

import numpy as np

CACHE_HEADER = "x-pathway-cache"

_ENABLED_ENV = "PATHWAY_ROUTER_CACHE"
_SIZE_ENV = "PATHWAY_ROUTER_CACHE_SIZE"
_MAX_LAG_ENV = "PATHWAY_ROUTER_CACHE_MAX_LAG_MS"
_TTL_ENV = "PATHWAY_ROUTER_CACHE_TTL_MS"
_WRITER_ENV = "PATHWAY_ROUTER_CACHE_WRITER"
_DIM_ENV = "PATHWAY_REPLICA_DIM"

# The cache subscribes as a reserved negative OBSERVER id: full-corpus
# subscriptions to a SHARDED writer are fenced for non-negative replica
# ids (a full-corpus member behind the router would duplicate keys in
# every merge), but an observer never sits behind the router — negative
# ids pass the torn-map guard and receive every shard's deltas.  Its
# wire leg is tagged ``repl:observe`` so Fault Forge can delay/drop the
# invalidation feed without touching the replica fan-out.

# score slack for the would-enter-the-top-k test: the replica scores on
# device (f32 XLA), the cache re-scores in numpy — evict anything within
# one part in 10^6 of the worst kept match instead of betting an exact
# answer on last-ulp agreement.  Ties ALWAYS evict: the device top-k
# breaks them by corpus slot order, which the cache cannot know.
_SCORE_EPS = 1e-6


def cache_enabled_via_env() -> bool:
    """``PATHWAY_ROUTER_CACHE=1`` arms the hot-tenant result cache on
    the failover router.  Off (the default) keeps the router request
    path byte-identical to the cache-less plane."""
    return os.environ.get(_ENABLED_ENV, "0").lower() in ("1", "true", "yes")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "") or str(default)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


def fingerprint(body: bytes) -> tuple[str, dict] | None:
    """Canonical request identity: the sorted-key JSON of the body.
    None = not a JSON object → not cacheable (the KNN read contract is
    a JSON body; anything else is passed through uncached)."""
    try:
        values = json.loads(body or b"{}")
    except ValueError:
        return None
    if not isinstance(values, dict):
        return None
    canon = json.dumps(values, sort_keys=True, separators=(",", ":"))
    return hashlib.blake2b(canon.encode(), digest_size=16).hexdigest(), values


def _k_of(values: dict) -> int | None:
    """The request's top-k, or None when it is not a usable number —
    such a read is not cacheable, but it must still reach the replica
    (whose structured error beats a router-side crash)."""
    try:
        k = int(values.get("k", 3))
    except (TypeError, ValueError):
        return None
    return k if k > 0 else None


class _Entry:
    __slots__ = (
        "payload",
        "headers",
        "qvec",
        "keys",
        "worst_score",
        "full",
        "scoreable",
        "stored_at",
        "tick",
        "bound",
        "seq",
    )

    def __init__(
        self,
        payload: bytes,
        headers: dict,
        qvec: np.ndarray | None,
        keys: frozenset,
        worst_score: float,
        full: bool,
        tick: int,
    ):
        self.payload = payload
        self.headers = headers
        self.qvec = qvec
        self.keys = keys
        self.worst_score = worst_score
        self.full = full
        # a query the cache cannot re-score (no vector derivable, or a
        # metric it does not know) stays correct by evicting on ANY
        # upsert instead of the score test
        self.scoreable = qvec is not None
        self.stored_at = time.monotonic()
        self.tick = tick
        # worst-kept-score bound for the sublinear upsert test: by
        # Cauchy-Schwarz dot(q, d) <= |q|·|d|, so an upserted doc of
        # norm |d| can only enter this entry's top-k when
        # |d| >= (worst - slack) / |q|.  Entries sit in a sorted bound
        # index; one bisect per tick finds the prefix that needs real
        # scoring instead of re-scoring every cached entry.
        if self.full and self.scoreable:
            slack = _SCORE_EPS * max(1.0, abs(worst_score))
            qn = float(np.linalg.norm(qvec))
            need = worst_score - slack
            if qn > 0.0:
                self.bound = need / qn
            else:
                # a zero query scores every doc 0: evictable iff the
                # bound is already <= 0, never otherwise
                self.bound = -np.inf if need <= 0.0 else np.inf
        else:
            self.bound = -np.inf  # evicts on ANY upsert (no score test)
        self.seq = 0  # bound-index tie-break, assigned at store


class ResultCache:
    """Bounded LRU of KNN read results with delta-exact invalidation.

    ``dim`` is the corpus embedding dimension (needed to re-derive the
    query vector of ``query``-text reads via the deterministic
    :func:`~pathway_tpu.serving.replica.text_vector`); ``metric`` must
    match the serving index (``cosine``/``dot`` are score-tested,
    anything else falls back to evict-on-any-upsert)."""

    def __init__(
        self,
        capacity: int | None = None,
        *,
        dim: int | None = None,
        metric: str = "cosine",
        max_lag_ms: float | None = None,
        ttl_ms: float | None = None,
    ):
        if capacity is None:
            capacity = int(os.environ.get(_SIZE_ENV, "1024") or 1024)
        self.capacity = max(int(capacity), 1)
        if dim is None:
            dim = int(os.environ.get(_DIM_ENV, "32") or 32)
        self.dim = int(dim)
        self.metric = metric
        self.max_lag_s = (
            _env_float(_MAX_LAG_ENV, 5000.0)
            if max_lag_ms is None
            else float(max_lag_ms)
        ) / 1000.0
        self.ttl_s = (
            _env_float(_TTL_ENV, 2000.0) if ttl_ms is None else float(ttl_ms)
        ) / 1000.0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # reverse index: corpus key -> cache keys of entries whose
        # result set contains it (the deletion/containment eviction)
        self._by_key: dict[int, set] = {}
        # sublinear upsert invalidation: entries sorted by their
        # worst-kept-score bound (see _Entry.bound) — one bisect per
        # tick selects the prefix an upserted doc could possibly enter;
        # everything past it provably survives WITHOUT re-scoring.
        # (bound, seq, ck) tuples: seq breaks ties so mixed-type cache
        # keys never get compared.
        self._bound_index: list[tuple[float, int, tuple]] = []
        self._entry_seq = 0
        self._client: Any = None
        self._seen_incarnation = -1
        # newest tick ever handed to ingest(), maintained under _lock.
        # The store() ordering guard compares against THIS (not just the
        # client's applied_tick, which bumps only after ingest returns):
        # an answer older than a tick whose eviction pass already ran
        # could never be evicted by it, so it must not be cached.
        self._seen_tick = -1
        from pathway_tpu.observability import REGISTRY

        self._m_lookups = REGISTRY.counter(
            "pathway_router_cache_lookups_total",
            "router result-cache lookups by outcome (hit = answered "
            "with zero replica hops; miss; bypass_lag = invalidation "
            "stream lagging past the bound; bypass_uncacheable = "
            "non-JSON body)",
            labelnames=("outcome",),
        )
        self._m_evictions = REGISTRY.counter(
            "pathway_router_cache_evictions_total",
            "cache entry evictions by reason (delta_contains = a "
            "changed key was in the result set; delta_enters = an "
            "upserted doc would enter the top-k; delta_notfull = "
            "upsert against an under-filled result set; lru; ttl)",
            labelnames=("reason",),
        )
        self._m_flushes = REGISTRY.counter(
            "pathway_router_cache_flushes_total",
            "whole-cache flushes (incarnation = writer takeover bumped "
            "the incarnation; resync = subscription fell off the ring)",
            labelnames=("reason",),
        )
        self._m_size = REGISTRY.gauge(
            "pathway_router_cache_entries",
            "live router result-cache entries",
        )
        # the registry holds gauge callbacks forever: weak ref so a
        # torn-down router's cache can be collected (reads 0 after)
        import weakref

        ref = weakref.ref(self)
        self._m_size.set_function(
            lambda: len(c._entries) if (c := ref()) is not None else 0
        )

    # --- delta-stream subscription ----------------------------------------

    def attach_stream(
        self,
        writer_host: str,
        writer_port: int,
        *,
        endpoints: list[tuple[str, int]] | None = None,
    ) -> None:
        """Subscribe to the writer's consolidated per-tick deltas (the
        invalidation feed).  Shard ``-1`` receives the FULL corpus
        stream, so one subscription serves sharded planes too."""
        from pathway_tpu.parallel.replicate import (
            OBSERVER_ID,
            DeltaStreamClient,
        )

        if self._client is not None:
            raise RuntimeError("result cache already has a delta stream")
        self._client = DeltaStreamClient(
            writer_host,
            writer_port,
            OBSERVER_ID,
            0,
            on_deltas=self.ingest,
            on_resync=self._on_resync,
            endpoints=endpoints,
        )
        self._client.start()

    def _on_resync(self) -> int:
        # the subscription fell off the writer's retained-delta ring:
        # ticks were missed for good, so nothing cached is trustworthy
        self.flush("resync")
        c = self._client
        return max(c.newest_known, 0) if c is not None else 0

    def close(self) -> None:
        c = self._client
        self._client = None
        if c is not None:
            c.close()

    def stream_staleness_s(self) -> float | None:
        """Seconds the invalidation feed may be behind the writer; None
        when no stream is attached (TTL mode) or while disconnected."""
        c = self._client
        return c.staleness_seconds() if c is not None else None

    @property
    def applied_tick(self) -> int:
        c = self._client
        return c.applied_tick if c is not None else -1

    # --- invalidation ------------------------------------------------------

    def _prep_vec(self, vec: Any) -> np.ndarray | None:
        v = np.asarray(vec, dtype=np.float32).reshape(-1)
        if self.metric == "cosine":
            n = float(np.linalg.norm(v))
            return v / n if n > 0 else v
        if self.metric == "dot":
            return v
        return None  # unknown metric: entries fall back to evict-on-upsert

    def ingest(self, tick: int, batches: list) -> None:
        """Apply one tick's consolidated corpus deltas (the
        DeltaStreamClient ``on_deltas`` callback; tests call it
        directly).  Evicts exactly the entries whose result sets could
        contain the tick's changed keys."""
        c = self._client
        if c is not None:
            inc = c.writer_incarnation
            if inc > self._seen_incarnation:
                if self._seen_incarnation >= 0:
                    # writer takeover: the new incarnation's history may
                    # not extend the old one's — nothing cached is
                    # trustworthy
                    self.flush("incarnation")
                self._seen_incarnation = inc
        removed: list[int] = []
        upserted: list[tuple[int, Any]] = []
        for b in batches:
            for key, diff, vals in b.iter_rows():
                if diff > 0:
                    upserted.append((int(key), vals[0] if vals else None))
                else:
                    removed.append(int(key))
        changed = {k for k, _v in upserted}
        changed.update(removed)
        dvecs = [
            self._prep_vec(v) if v is not None else None
            for _k, v in upserted
        ]
        # the covering prefix of the bound index: the largest upserted
        # doc norm decides which entries an upsert could possibly enter
        # (a None dvec — vectorless upsert or unknown metric — defeats
        # the bound, so every entry becomes a candidate, matching the
        # pre-index scan)
        blind = any(d is None for d in dvecs)
        max_norm = max(
            (float(np.linalg.norm(d)) for d in dvecs if d is not None),
            default=None,
        )
        with self._lock:
            # recorded BEFORE any eviction work so a store() racing
            # this tick sees it and refuses answers this pass could
            # never evict
            if tick > self._seen_tick:
                self._seen_tick = tick
            if not changed:
                return
            # candidates, each a SUBLINEAR selection: containment from
            # the per-key reverse index; upsert entrants from the bound
            # index prefix (a changed key tests a bound instead of
            # re-scoring every cached entry)
            cand: dict[tuple, None] = {}
            for key in changed:
                for ck in self._by_key.get(key, ()):
                    cand[ck] = None
            if upserted:
                if blind:
                    cand.update((ck, None) for ck in self._entries)
                elif max_norm is not None:
                    import bisect

                    hi = bisect.bisect_right(
                        self._bound_index, (max_norm, 1 << 62, ())
                    )
                    cand.update(
                        (ck, None)
                        for _b, _s, ck in self._bound_index[:hi]
                    )
            # snapshot only the candidates' eviction-relevant fields:
            # scoring runs OUTSIDE the lock so router lookups and
            # stores never stall behind a churny invalidation tick
            snapshot = [
                (ck, e.keys, e.worst_score, e.full, e.scoreable, e.qvec)
                for ck in cand
                if (e := self._entries.get(ck)) is not None
            ]
        evict: dict[tuple, str] = {}
        for ck, keys, worst, full, scoreable, qvec in snapshot:
            if keys & changed:
                evict[ck] = "delta_contains"
                continue
            for dvec in dvecs:
                if not full:
                    evict[ck] = "delta_notfull"
                    break
                if not scoreable or dvec is None:
                    evict[ck] = "delta_enters"
                    break
                s = float(np.dot(qvec, dvec))
                slack = _SCORE_EPS * max(1.0, abs(worst))
                if s >= worst - slack:
                    evict[ck] = "delta_enters"
                    break
        if not evict:
            return
        with self._lock:
            for ck, reason in evict.items():
                e = self._entries.get(ck)
                # an entry replaced mid-pass by a store carrying an
                # answer PAST this tick already reflects the delta.
                # Equal-tick answers still drop: same-tick merge frames
                # (lockstep second publishers, reconnect boundary
                # replays) mean tick t can grow after an answer at t.
                if e is None or e.tick > tick:
                    continue
                self._drop_locked(ck)
                self._m_evictions.labels(reason).inc()

    def _drop_locked(self, ck: tuple) -> None:
        e = self._entries.pop(ck, None)
        if e is None:
            return
        for key in e.keys:
            s = self._by_key.get(key)
            if s is not None:
                s.discard(ck)
                if not s:
                    del self._by_key[key]
        import bisect

        i = bisect.bisect_left(self._bound_index, (e.bound, e.seq, ck))
        if (
            i < len(self._bound_index)
            and self._bound_index[i][1] == e.seq
        ):
            self._bound_index.pop(i)

    def flush(self, reason: str) -> None:
        with self._lock:
            self._entries.clear()
            self._by_key.clear()
            self._bound_index.clear()
        self._m_flushes.labels(reason).inc()

    # --- request path -------------------------------------------------------

    @staticmethod
    def _cache_key(
        tenant: str | None,
        path: str,
        fp: str,
        k: int,
        max_staleness_ms: float | None,
    ) -> tuple:
        return (tenant or "", path or "", fp, int(k), max_staleness_ms)

    def _bypass(self, max_staleness_ms: float | None) -> str | None:
        """Non-None = reason the cache must not answer right now."""
        if self._client is None:
            return None  # TTL mode: per-entry expiry decides
        lag = self.stream_staleness_s()
        if lag is None:
            return "bypass_lag"  # disconnected: no invalidation feed
        if lag > self.max_lag_s:
            return "bypass_lag"
        if max_staleness_ms is not None and lag * 1000.0 > max_staleness_ms:
            return "bypass_lag"
        return None

    def lookup(
        self,
        tenant: str | None,
        body: bytes,
        max_staleness_ms: float | None,
        path: str = "",
    ) -> tuple[int, bytes, dict] | None:
        """A cached answer for this read, or None (forward to a
        replica).  Hits carry the freshness headers the degrade
        contract requires."""
        reason = self._bypass(max_staleness_ms)
        if reason is not None:
            self._m_lookups.labels(reason).inc()
            return None
        fped = fingerprint(body)
        if fped is None:
            self._m_lookups.labels("bypass_uncacheable").inc()
            return None
        fp, values = fped
        k = _k_of(values)
        if k is None:
            self._m_lookups.labels("bypass_uncacheable").inc()
            return None
        ck = self._cache_key(tenant, path, fp, k, max_staleness_ms)
        now = time.monotonic()
        with self._lock:
            e = self._entries.get(ck)
            if e is not None and self._client is None:
                # TTL mode: the request's own staleness bound tightens
                # the expiry — a bounded read must never get an answer
                # older than it asked for just because the TTL allows it
                ttl = self.ttl_s
                if max_staleness_ms is not None:
                    ttl = min(ttl, max_staleness_ms / 1000.0)
                if now - e.stored_at > ttl:
                    self._drop_locked(ck)
                    self._m_evictions.labels("ttl").inc()
                    e = None
            if e is None:
                self._m_lookups.labels("miss").inc()
                return None
            self._entries.move_to_end(ck)
            payload, base_headers, tick = e.payload, e.headers, e.tick
            age = now - e.stored_at
            # freshness captured under the SAME lock that proved the
            # entry live: the entry is provably equal to a fresh answer
            # as of the stream position it survived, so these are the
            # response's freshness claims (a tick landing after this
            # point is the same as the read arriving a moment earlier)
            streamed = self._client is not None
            applied = self.applied_tick if streamed else None
            lag = self.stream_staleness_s() if streamed else None
        self._m_lookups.labels("hit").inc()
        headers = dict(base_headers)
        headers[CACHE_HEADER] = "hit"
        if applied is not None:
            headers["x-pathway-applied-tick"] = str(applied)
            headers["x-pathway-staleness-seconds"] = f"{(lag or 0.0):.3f}"
        else:
            headers.setdefault("x-pathway-applied-tick", str(tick))
            headers["x-pathway-staleness-seconds"] = f"{age:.3f}"
        return 200, payload, headers

    def store(
        self,
        tenant: str | None,
        body: bytes,
        max_staleness_ms: float | None,
        status: int,
        payload: bytes,
        headers: dict,
        path: str = "",
    ) -> bool:
        """Consider one routed response for caching.  Only fresh 200s
        carrying the KNN ``matches`` contract are kept."""
        if status != 200:
            return False
        hl = {k.lower(): v for k, v in headers.items()}
        if hl.get("x-pathway-stale"):
            return False  # degraded answers are never cached
        fped = fingerprint(body)
        if fped is None:
            return False
        fp, values = fped
        try:
            doc = json.loads(payload)
        except ValueError:
            return False
        if not isinstance(doc, dict):
            return False  # 200s outside the KNN contract pass through
        matches = doc.get("matches")
        if not isinstance(matches, list):
            return False
        tick_raw = hl.get("x-pathway-applied-tick")
        try:
            tick = int(tick_raw) if tick_raw is not None else -1
        except ValueError:
            tick = -1
        k = _k_of(values)
        if k is None:
            return False
        try:
            keys = frozenset(int(m[0]) for m in matches)
            worst = min(float(m[1]) for m in matches) if matches else 0.0
        except (TypeError, ValueError, IndexError):
            return False
        qvec: np.ndarray | None = None
        if values.get("vec") is not None:
            try:
                qvec = self._prep_vec(values["vec"])
            except (TypeError, ValueError):
                qvec = None
        elif values.get("query") is not None:
            from pathway_tpu.serving.replica import text_vector

            qvec = self._prep_vec(text_vector(str(values["query"]), self.dim))
        entry = _Entry(
            payload,
            {
                k: v
                for k, v in headers.items()
                if k.lower()
                in ("content-type", "x-pathway-replica", "x-pathway-shards")
            },
            qvec,
            keys,
            worst,
            len(matches) >= k,
            tick,
        )
        ck = self._cache_key(tenant, path, fp, k, max_staleness_ms)
        with self._lock:
            if self._client is not None:
                # ordering guard, under the SAME lock ingest() updates
                # _seen_tick through: if the invalidation stream has
                # started (or finished) a tick PAST the answering
                # replica's applied tick, a delta this cache already
                # processed may postdate the answer — its eviction pass
                # could never cover this entry.  Skip the store.
                if tick < 0 or max(self._seen_tick, self.applied_tick) > tick:
                    return False
            self._drop_locked(ck)  # replace: unindex the old result set
            self._entry_seq += 1
            entry.seq = self._entry_seq
            import bisect

            bisect.insort(
                self._bound_index, (entry.bound, entry.seq, ck)
            )
            self._entries[ck] = entry
            self._entries.move_to_end(ck)
            for key in keys:
                self._by_key.setdefault(key, set()).add(ck)
            while len(self._entries) > self.capacity:
                oldest = next(iter(self._entries))
                self._drop_locked(oldest)
                self._m_evictions.labels("lru").inc()
        return True

    # --- introspection ------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def entry_keys(self) -> list[tuple]:
        with self._lock:
            return list(self._entries)


def cache_from_env() -> ResultCache | None:
    """The router's result cache when ``PATHWAY_ROUTER_CACHE=1``, with
    the invalidation stream attached when
    ``PATHWAY_ROUTER_CACHE_WRITER=host:port`` names the writer's delta
    endpoint — else None: the total escape hatch (no cache object, no
    cache branch on the request path)."""
    if not cache_enabled_via_env():
        return None
    cache = ResultCache()
    writer = os.environ.get(_WRITER_ENV, "").strip()
    if writer:
        host, _, port = writer.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"{_WRITER_ENV}={writer!r} is not host:port"
            )
        cache.attach_stream(host, int(port))
    return cache
