"""Dynamic micro-batcher: coalesces admitted requests and releases them
to the engine in earliest-deadline-first order.

A flush happens when ``max_batch_size`` requests have coalesced, when
the OLDEST queued request has waited ``max_wait_ms`` (bounded added
latency even at low load), or immediately in drain mode. Expired
requests are rejected at flush — they never reach the engine, so a dead
deadline cannot burn a batch slot. The dispatch callback runs on the
batcher thread and atomically inserts the whole batch into the target
``InputSession``, so one engine tick (and therefore one jitted
embed/KNN batch) carries the whole release.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Callable

from pathway_tpu.serving.admission import DeadlineExceeded
from pathway_tpu.serving.config import QoSConfig


class MicroBatcher:
    """``put`` is thread-safe (called from aiohttp handlers); flushing
    runs on one dedicated daemon thread."""

    def __init__(
        self,
        config: QoSConfig,
        dispatch: Callable[[list], None],
        reject: Callable[[Any, BaseException], None],
        capacity: Callable[[], int] | None = None,
        name: str = "surge-gate",
        order: Callable[[Any], Any] | None = None,
    ):
        self.config = config
        self._dispatch = dispatch
        self._reject = reject
        # heap ordering key; default = plain EDF (request deadline).
        # Tenant Weave passes a weighted-fair key (vfinish, deadline) so
        # a hot tenant's backlog drains behind the tail's fresh requests
        self._order = order if order is not None else (lambda r: r.deadline)
        # dispatch-window backpressure: how many more requests may be
        # released right now (gate: dispatch_window - dispatched_pending).
        # None = unbounded. Bounded capacity is what makes the ADMISSION
        # queue the place where overload accumulates (and sheds) instead
        # of the engine's unbounded InputSession.
        self._capacity = capacity
        self._cond = threading.Condition()
        # EDF: (deadline, seq) heap key; seq breaks ties FIFO
        self._heap: list[tuple[float, int, Any]] = []
        self._seq = 0
        self._oldest_at: float | None = None  # enqueue time of oldest item
        self._closing = False
        self._draining = False
        self.flushes = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )
        self._thread.start()

    def __len__(self) -> int:
        with self._cond:
            return len(self._heap)

    def put(self, req: Any) -> None:
        """Enqueue an admitted request (req must expose ``.deadline``)."""
        now = time.monotonic()
        with self._cond:
            if self._closing:
                raise RuntimeError("micro-batcher is closed")
            self._seq += 1
            heapq.heappush(self._heap, (self._order(req), self._seq, req))
            if self._oldest_at is None:
                self._oldest_at = now
            self._cond.notify()

    def drain(self) -> None:
        """Flush everything queued as fast as possible; new ``put``s are
        still accepted until ``close`` (admission already sheds them)."""
        with self._cond:
            self._draining = True
            self._cond.notify()

    def notify(self) -> None:
        """Wake the flush loop (dispatch capacity may have freed up)."""
        with self._cond:
            self._cond.notify()

    def close(self, reject_queued: BaseException | None = None) -> None:
        """Stop the flush thread. ``reject_queued`` (e.g. a ShedError)
        fails whatever is still queued instead of dispatching it."""
        with self._cond:
            self._closing = True
            leftovers = []
            if reject_queued is not None:
                leftovers = [r for _, _, r in self._heap]
                self._heap = []
                self._oldest_at = None
            self._cond.notify()
        for req in leftovers:
            self._reject(req, reject_queued)
        # close may run from a GC finalizer on an arbitrary thread —
        # including this batcher's own (joining yourself raises)
        if (
            self._thread.is_alive()
            and self._thread is not threading.current_thread()
        ):
            self._thread.join(timeout=5)

    # --- flush loop -------------------------------------------------------

    def _room(self) -> int:
        """Dispatch capacity right now (drain/close ignore the window —
        the engine still processes whatever is left)."""
        if self._capacity is None or self._draining or self._closing:
            return self.config.max_batch_size
        return self._capacity()

    def _wait_for_flush_condition(self) -> bool:
        """Hold the lock; return False when closing with nothing left."""
        cfg = self.config
        while True:
            if self._heap:
                ripe = (
                    len(self._heap) >= cfg.max_batch_size
                    or self._draining
                    or self._closing
                )
                if not ripe:
                    budget = (
                        self._oldest_at + cfg.max_wait_ms / 1000.0
                    ) - time.monotonic()
                    if budget > 0:
                        self._cond.wait(budget)
                        continue
                if self._room() >= 1:
                    return True
                # dispatch window full: wait for a complete() notify.
                # The bounded wait doubles as an expiry sweep — requests
                # whose deadline passes while stuck here must be dropped
                # even if the engine never frees capacity.
                self._cond.wait(0.05)
                self._drop_expired_locked()
            elif self._closing:
                return False
            else:
                self._cond.wait()

    def steal(self, selector: Callable[[list], Any]) -> Any:
        """Remove and return ONE queued request chosen by ``selector``
        (called under the lock with the queued requests; returns a
        request or None).  Tenant Weave's queue-full eviction: the gate
        rejects the stolen request itself, charging the shed to the
        over-share tenant instead of the arriving tail request."""
        with self._cond:
            if not self._heap:
                return None
            victim = selector([r for _k, _s, r in self._heap])
            if victim is None:
                return None
            self._heap = [e for e in self._heap if e[2] is not victim]
            heapq.heapify(self._heap)
            if not self._heap:
                self._oldest_at = None
            return victim

    def _drop_expired_locked(self) -> None:
        now = time.monotonic()
        # expiry always reads the request's DEADLINE — the heap key may
        # be a weighted-fair tag, not the deadline itself
        if not any(r.deadline < now for _d, _s, r in self._heap):
            return
        keep, dead = [], []
        for d, s, r in self._heap:
            (dead if r.deadline < now else keep).append((d, s, r))
        self._heap = keep
        heapq.heapify(self._heap)
        if not self._heap:
            self._oldest_at = None
        for _d, _s, req in dead:
            self._reject(req, DeadlineExceeded())

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._wait_for_flush_condition():
                    return
                batch = [
                    heapq.heappop(self._heap)[2]
                    for _ in range(
                        min(
                            len(self._heap),
                            self.config.max_batch_size,
                            max(1, self._room()),
                        )
                    )
                ]
                # remaining items started a fresh wait window: their
                # original enqueue times are older, but re-arming from
                # now keeps the invariant "no flush later than
                # oldest + max_wait" approximately while staying O(1)
                self._oldest_at = time.monotonic() if self._heap else None
            now = time.monotonic()
            # complement partition: a pathological deadline (NaN) must
            # land in exactly one bucket, never silently vanish
            live = [r for r in batch if r.deadline >= now]
            dead = [r for r in batch if not (r.deadline >= now)]
            for req in dead:
                self._reject(req, DeadlineExceeded())
            if live:
                try:
                    self._dispatch(live)
                except Exception as exc:  # dispatch must not kill the loop
                    for req in live:
                        self._reject(req, exc)
            self.flushes += 1
