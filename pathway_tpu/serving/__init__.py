"""Surge Gate: the serving QoS subsystem — dynamic micro-batching,
deadline-aware admission control and graceful overload/drain for the
REST serving path.

Layering: ``io/http`` (ingress) builds a ``SurgeGate`` per endpoint when
``rest_connector(..., qos=QoSConfig(...))`` is passed (or
``PATHWAY_SERVING_ENABLED=1``); the gate feeds the engine's
``InputSession`` in bucketed releases; ``engine/index_node`` and the
embedders consult :mod:`pathway_tpu.serving.deadline` so expired work is
dropped before it burns a device batch slot. Everything here is
stdlib-only — safe to import from the engine layer.
"""

from pathway_tpu.serving.admission import (
    AdmissionController,
    DeadlineExceeded,
    ShedError,
    TokenBucket,
)
from pathway_tpu.serving.batcher import MicroBatcher
from pathway_tpu.serving.config import (
    QoSConfig,
    default_bucket_ladder,
    serving_enabled_via_env,
)
from pathway_tpu.serving.gate import (
    PendingRequest,
    SurgeGate,
    drain_all,
    gates,
)
from pathway_tpu.serving import degrade
from pathway_tpu.serving.tenancy import (
    TenancyConfig,
    TenantLabeler,
    TenantLedger,
    parse_weight_classes,
    tenancy_enabled_via_env,
)

# Replica Shield (serving/replica.py, serving/router.py) is NOT eagerly
# imported: the replica/router roles pull aiohttp and the replication
# wire, which the engine layer (which imports this package on every
# run) never needs.  `ReplicaServer` / `FailoverRouter` resolve lazily.
_LAZY = {
    "ReplicaServer": ("pathway_tpu.serving.replica", "ReplicaServer"),
    "FailoverRouter": ("pathway_tpu.serving.router", "FailoverRouter"),
    "ResultCache": ("pathway_tpu.serving.result_cache", "ResultCache"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])


__all__ = [
    "FailoverRouter",
    "ReplicaServer",
    "ResultCache",
    "TenancyConfig",
    "TenantLabeler",
    "TenantLedger",
    "parse_weight_classes",
    "tenancy_enabled_via_env",
    "AdmissionController",
    "DeadlineExceeded",
    "MicroBatcher",
    "PendingRequest",
    "QoSConfig",
    "ShedError",
    "SurgeGate",
    "TokenBucket",
    "default_bucket_ladder",
    "degrade",
    "drain_all",
    "gates",
    "serving_enabled_via_env",
]
