"""Replica Shield read replicas — the horizontal read plane.

A replica is a NEW process role beside the lockstep mesh group: it runs
no engine graph and joins no barriers.  It holds a full copy of the
serving index, built in two steps and kept fresh by a third:

1. **Hydrate** from the newest committed snapshot generation in the
   writer's persistence store (``hydrate_index_state`` walks the PR-8
   retained-generation list newest-first and loads the
   ``ExternalIndexNode`` state blob — the same artifact the PR-7 mmap
   recovery path restores), giving the corpus as of the snapshot's
   tick.
2. **Subscribe** to the writer's delta stream
   (parallel/replicate.py) from that tick: the ring tail replays, then
   live consolidated per-tick deltas apply.  A subscription that fell
   off the writer's bounded ring answers ``resync`` and the replica
   re-hydrates from the (by now newer) generation instead.
3. **Serve** reads over HTTP with explicit freshness: every response
   carries ``x-pathway-replica`` / ``x-pathway-applied-tick`` /
   ``x-pathway-staleness-seconds``, stale answers add
   ``x-pathway-stale: true``, and a request's
   ``x-pathway-max-staleness-ms`` bound sheds with 503 + Retry-After
   instead of silently serving older data — the same header contract
   PR 8's degraded single-process path established
   (serving/degrade.py), now per replica.

Freshness for ROUTING: ``ready`` is True only once the replica has
caught up with the writer's newest published tick since its current
subscription — a restarted replica is only re-admitted by the failover
router (serving/router.py) after it clears this bound.

Observability: ``pathway_replica_staleness_seconds`` (gauge, labeled by
replica), ``pathway_replica_applied_tick``, request/shed counters.
Monotone ``applied_tick`` is exported on every response and in
``GET /replica/health``.

``python -m pathway_tpu.serving.replica`` runs the env-configured KNN
replica (TpuDenseKnnIndex + the deterministic ``text_vector``
pseudo-embedder) — the role the chaos bench and the multi-process tests
spawn under the Phoenix Mesh supervisor.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Callable

import numpy as np

from pathway_tpu.observability.journal import record as _journal_record
from pathway_tpu.observability.tracing import get_tracer

_STALE_AFTER_MS_ENV = "PATHWAY_REPLICA_STALE_AFTER_MS"


def text_vector(text: str, dim: int) -> np.ndarray:
    """Deterministic pseudo-embedding: the same text always maps to the
    same unit vector, on the writer and on every replica — so the
    replicated serving plane (and its tests/bench) needs no shared
    encoder weights.  Not a semantic embedder; similar ONLY for equal
    text prefixes by construction (chunks are seeded per token)."""
    acc = np.zeros(dim, dtype=np.float64)
    for i, tok in enumerate(str(text).split() or [""]):
        seed = hashlib.blake2b(
            f"{i}:{tok}".encode(), digest_size=8
        ).digest()
        rng = np.random.default_rng(int.from_bytes(seed, "little"))
        acc += rng.standard_normal(dim)
    norm = float(np.linalg.norm(acc))
    if norm > 0:
        acc /= norm
    return acc.astype(np.float32)


def staleness_bound_exceeded(
    staleness: float | None, stale: bool, max_raw: str | None
) -> bool:
    """The ``x-pathway-max-staleness-ms`` shed predicate — ONE rule for
    every route that answers from this replica's corpus (/query reads
    AND /generate, whose output is conditioned on it).  Unknown
    staleness counts as over any finite bound; a caught-up replica is
    FRESH (staleness ~0 between heartbeats), so bound 0 sheds only
    when genuinely stale.  Unparseable/non-finite bounds are ignored
    (no bound)."""
    import math

    if max_raw is None:
        return False
    try:
        bound_ms = float(max_raw)
    except ValueError:
        return False
    if not math.isfinite(bound_ms):
        return False
    over = staleness is None or staleness * 1000.0 > bound_ms
    return over or (bound_ms <= 0.0 and stale)


def hydrate_index_state(
    store: Any, node_class: str = "ExternalIndexNode"
) -> tuple[Any, int, int] | None:
    """Load the newest committed index snapshot from a writer's
    persistence store: ``(index_state, tick, gen)`` or None when no
    generation holds an index yet.

    Candidates are walked newest-first — the current ``state`` then the
    PR-8 ``retained_states`` list (legacy ``prev_state``) — so a torn
    latest generation degrades to the previous committed one instead of
    failing the hydrate, mirroring the group-min restore."""
    from pathway_tpu.persistence._runtime_glue import (
        PersistenceDriver,
        _META_KEY,
    )

    raw = store.get(_META_KEY)
    if raw is None:
        return None
    meta = json.loads(raw.decode())
    candidates = [meta.get("state")]
    candidates += [
        r.get("state") for r in reversed(meta.get("retained_states", []))
    ]
    if meta.get("prev_state"):
        candidates.append(meta["prev_state"])
    seen: set[int] = set()
    for snap in candidates:
        if not snap or int(snap.get("gen", -1)) in seen:
            continue
        gen = int(snap["gen"])
        seen.add(gen)
        for ident, cls in snap.get("nodes", {}).items():
            if cls != node_class:
                continue
            blob = store.get(PersistenceDriver._state_key(gen, ident))
            if blob is None:
                continue  # torn generation: fall back to an older one
            state = pickle.loads(blob)
            if not isinstance(state, dict) or "index_state" not in state:
                continue
            return (
                state["index_state"],
                int(snap.get("time", 0)),
                gen,
            )
    return None


_M: dict | None = None


def _metrics() -> dict:
    global _M
    if _M is None:
        from pathway_tpu.observability import REGISTRY

        _M = {
            "staleness": REGISTRY.gauge(
                "pathway_replica_staleness_seconds",
                "seconds since this replica last confirmed it was caught "
                "up with the writer's newest published tick, by replica",
                labelnames=("replica",),
            ),
            "applied": REGISTRY.gauge(
                "pathway_replica_applied_tick",
                "newest writer tick this replica has applied (monotone)",
                labelnames=("replica",),
            ),
            "requests": REGISTRY.counter(
                "pathway_replica_requests_total",
                "read requests served by this replica, by status class",
                labelnames=("replica", "status"),
            ),
            "resyncs": REGISTRY.counter(
                "pathway_replica_resyncs_total",
                "full re-hydrates (subscription fell off the writer's "
                "retained-delta ring)",
                labelnames=("replica",),
            ),
        }
    return _M


def default_knn_responder(server: "ReplicaServer", values: dict) -> dict:
    """Answer a KNN read against the replica's corpus: ``vec`` (raw
    query vector) or ``query`` (text through :func:`text_vector`), plus
    ``k``.  Matches return as ``[key, score]`` pairs, best first."""
    k = int(values.get("k", 3))
    if values.get("vec") is not None:
        vec = np.asarray(values["vec"], dtype=np.float32)
    else:
        vec = text_vector(str(values.get("query", "")), server.dim)
    results = server.search([(vec, k, None)])[0]
    return {"matches": [[int(key), float(score)] for key, score in results]}


class ReplicaServer:
    """One read replica: hydrated index + delta subscription + HTTP.

    ``index_factory`` builds the (empty) index object; ``store_root``
    (optional) hydrates it from the writer's persistence store;
    ``writer_port`` subscribes to the delta stream.  ``responder(server,
    values) -> payload`` answers one read (default: KNN over ``vec`` /
    ``query``+``k``).  ``qos`` (a serving.QoSConfig) bounds concurrent
    reads with the Surge-Gate admission controller — the router load-
    balances IN FRONT of this gate, so a saturated replica sheds 429
    and the router steers elsewhere."""

    def __init__(
        self,
        *,
        replica_id: int,
        index_factory: Callable[[], Any],
        store_root: str | None = None,
        writer_host: str = "127.0.0.1",
        writer_port: int | None = None,
        writer_endpoints: list[tuple[str, int]] | None = None,
        http_host: str = "127.0.0.1",
        http_port: int = 0,
        route: str = "/query",
        responder: Callable[["ReplicaServer", dict], Any] | None = None,
        qos: Any = None,
        dim: int = 32,
        stale_after_ms: float | None = None,
        shard: int = -1,
        n_shards: int = 1,
    ):
        self.replica_id = int(replica_id)
        self.index_factory = index_factory
        self.store_root = store_root
        self.writer_host = writer_host
        self.writer_port = writer_port
        self.writer_endpoints = writer_endpoints
        self.http_host = http_host
        self.http_port = http_port
        self.route = route
        self.responder = responder or default_knn_responder
        self.dim = dim
        # Shard Harbor: this member owns one key range (jk-hash shard)
        # of the corpus; the writer fans it only that shard's deltas and
        # hydration drops foreign keys, so resident memory is ~1/S.  A
        # torn assignment (shard outside [0, n_shards)) is rejected at
        # BOOT, not discovered as silently-wrong answers.
        self.n_shards = max(int(n_shards), 1)
        self.shard = int(shard)
        if self.n_shards > 1 and not (0 <= self.shard < self.n_shards):
            raise ValueError(
                f"replica {replica_id}: shard {self.shard} is outside "
                f"the {self.n_shards}-shard assignment map (torn shard "
                "configuration rejected at boot)"
            )
        if self.n_shards == 1:
            self.shard = -1  # unsharded plane: full corpus
        if stale_after_ms is None:
            stale_after_ms = float(
                os.environ.get(_STALE_AFTER_MS_ENV, "3000") or 3000
            )
        self.stale_after_s = max(stale_after_ms, 0.0) / 1000.0
        self._has_stream = bool(
            writer_port is not None or writer_endpoints
        )
        self.index = index_factory()
        self.hydrated_tick = -1
        self.hydrated_gen = -1
        self._index_lock = threading.RLock()
        self._client: Any = None
        self._closed = False
        self.incarnation = int(
            os.environ.get("PATHWAY_MESH_INCARNATION", "0") or 0
        )
        m = _metrics()
        label = str(self.replica_id)
        self._m_requests = m["requests"]
        self._m_resyncs = m["resyncs"].labels(label)
        m["staleness"].labels(label).set_function(
            lambda: self.staleness_seconds() or 0.0
        )
        m["applied"].labels(label).set_function(
            lambda: float(self.applied_tick)
        )
        from pathway_tpu.serving.admission import AdmissionController
        from pathway_tpu.serving.tenancy import ledger_for

        # Tenant Weave: PATHWAY_TENANT_QOS=1 makes this replica's
        # admission tenant-aware (per-tenant fair-share buckets inside
        # the gate's capacity envelope) — the router forwards the
        # x-pathway-tenant header, so the shed lands on the hot tenant
        # at every member it is steered to
        self.tenant_ledger = (
            ledger_for(qos, route=f"replica{self.replica_id}")
            if qos is not None
            else None
        )
        self.admission = (
            AdmissionController(
                qos,
                route=f"replica{self.replica_id}",
                ledger=self.tenant_ledger,
            )
            if qos is not None
            else None
        )
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # Token Loom: extra POST routes mounted before start() —
        # generate.serving.attach_generate registers the /generate
        # handler (an async fn(http, request) -> StreamResponse) and
        # the decode scheduler here
        self.extra_post_routes: dict[str, Any] = {}
        self.generate_scheduler: Any = None
        self._http = _ReplicaHttp(self)

    # --- state ------------------------------------------------------------

    @property
    def applied_tick(self) -> int:
        c = self._client
        if c is not None:
            return max(c.applied_tick, self.hydrated_tick)
        return self.hydrated_tick

    @property
    def ready(self) -> bool:
        """Freshness bound for router admission: hydrated AND caught up
        with the writer's newest published tick since the current
        subscription.  With no delta stream configured (snapshot-only
        replica) readiness is just successful hydration."""
        c = self._client
        if c is None:
            return self.hydrated_tick >= 0 or not self._has_stream
        return bool(c.caught_up)

    def staleness_seconds(self) -> float | None:
        c = self._client
        if c is None:
            return None
        return c.staleness_seconds()

    def is_stale(self) -> bool:
        """A response right now would be stale: never caught up, the
        catch-up confirmation has aged past the bound (writer dead or
        partitioned), or the stream is behind."""
        c = self._client
        if c is None:
            return self._has_stream
        s = c.staleness_seconds()
        if s is None:
            return True
        return s > self.stale_after_s

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "ReplicaServer":
        self.hydrate()
        if self.writer_port is not None or self.writer_endpoints:
            from pathway_tpu.parallel.replicate import DeltaStreamClient

            eps = self.writer_endpoints or [
                (self.writer_host, int(self.writer_port))
            ]
            self._client = DeltaStreamClient(
                eps[0][0],
                eps[0][1],
                self.replica_id,
                from_tick=self.hydrated_tick,
                on_deltas=self._apply_deltas,
                # store-less replicas have no hydrate path: accept-the-
                # gap semantics (client converges on the writer's ring)
                # instead of waiting for a snapshot that can never come
                on_resync=self._resync if self.store_root else None,
                on_applied=self._on_applied,
                shard=self.shard,
                expect_shards=self.n_shards if self.n_shards > 1 else 0,
                endpoints=eps,
            )
            self._client.start()
        self._http.start()
        self.http_port = self._http.port
        # Tick Scope: a serving surface is now live — the
        # tickscope-coverage doctor rule INFOs if the flight recorder is
        # disabled while this replica serves. The memory provider hands
        # the replica's index residency to the same ledger the engine
        # execs report into (owner "replica:<id>").
        from pathway_tpu.observability import tickscope as _ts

        import weakref as _weakref

        _r = _weakref.ref(self)

        def _replica_memory():
            rep = _r()
            if rep is None or rep._closed:
                return {}
            _docs, nbytes = rep.corpus_stats()
            return {"index": max(int(nbytes), 0)}

        _ts.register_memory_provider(
            f"replica:{self.replica_id}", _replica_memory
        )
        _ts.mark_serving(True)
        return self

    def stop(self) -> None:
        self._closed = True
        if self._client is not None:
            self._client.close()
        if self.generate_scheduler is not None:
            self.generate_scheduler.stop()
        self._http.stop()
        from pathway_tpu.observability import tickscope as _ts

        _ts.unregister_memory_provider(f"replica:{self.replica_id}")

    # --- hydrate + deltas -------------------------------------------------

    def _open_store(self):
        from pathway_tpu.persistence.backends import FilesystemStore

        return FilesystemStore(self.store_root)

    def hydrate(self) -> int:
        """(Re-)hydrate the index from the newest committed generation;
        returns the hydrated tick (-1 when no store/snapshot exists —
        the replica then builds purely from the delta stream).  A
        sharded member drops every key outside its shard right after
        the load, so resident memory is ~1/S of the writer's corpus."""
        if self.store_root is None:
            return self.hydrated_tick
        with get_tracer().span(
            "replica.hydrate", root=True, replica=self.replica_id
        ) as span:
            got = hydrate_index_state(self._open_store())
            if got is None:
                return self.hydrated_tick
            index_state, tick, gen = got
            fresh = self.index_factory()
            kind, payload = index_state
            if kind == "dict":
                fresh.load_state(payload)
            else:
                fresh = payload
            if self.shard >= 0:
                self._filter_to_shard(fresh)
            with self._index_lock:
                self.index = fresh
                self.hydrated_tick = tick
                self.hydrated_gen = gen
            span.set_attribute("tick", tick)
            span.set_attribute("generation", gen)
        _journal_record(
            "replica-hydrated",
            f"replica {self.replica_id} hydrated generation {gen}",
            tick=tick,
            incarnation=self.incarnation,
            replica_id=self.replica_id,
            generation=gen,
        )
        return tick

    def _filter_to_shard(self, index: Any) -> None:
        """Drop hydrated keys this member does not own (the writer's
        snapshot holds the FULL corpus; the delta stream is already
        shard-filtered).  Prefers the index's compacting
        ``filter_keys`` (releases the backing buffers — the ~1/S
        memory claim); falls back to per-key ``remove``."""
        from pathway_tpu.parallel.replicate import corpus_shard_of

        keys_fn = getattr(index, "keys", None)
        if not callable(keys_fn):
            import logging

            logging.getLogger("pathway_tpu").warning(
                "replica %d: index %s exposes no keys(); serving the "
                "FULL hydrated corpus on a sharded plane",
                self.replica_id,
                type(index).__name__,
            )
            return
        keys = list(keys_fn())
        if not keys:
            return
        dest = corpus_shard_of(keys, self.n_shards)
        owned = {
            k for k, s in zip(keys, dest) if int(s) == self.shard
        }
        filt = getattr(index, "filter_keys", None)
        if callable(filt):
            filt(lambda k: k in owned)
            return
        for k in keys:
            if k not in owned:
                index.remove(k)

    def _resync(self) -> int:
        """Delta-stream callback: the subscription tick fell off the
        writer's bounded ring — beyond it, full re-hydrate (tentpole
        contract (c))."""
        self._m_resyncs.inc()
        _journal_record(
            "replica-resync",
            f"replica {self.replica_id} fell off the delta ring",
            tick=self.applied_tick,
            incarnation=self.incarnation,
            replica_id=self.replica_id,
        )
        return self.hydrate()

    # --- live resharding (Shard Flux) -------------------------------------

    def adopt_shard_map(self, shard: int, n_shards: int) -> None:
        """Adopt a NEW shard assignment without a process restart — the
        member-side half of a live reshard.  The old subscription closes
        (a resharded writer fences it at suback anyway — the transition
        guard), the resident corpus re-partitions under the new
        ownership (store-backed members re-hydrate so a MERGE gains its
        newly-owned foreign keys; store-less members can only narrow),
        and a fresh subscription opens with the new expectations.  The
        HTTP plane keeps serving throughout — the router's health poll
        sees ``ready`` flip false and back as the member catches up."""
        n_shards = max(int(n_shards), 1)
        shard = int(shard) if n_shards > 1 else -1
        if n_shards > 1 and not (0 <= shard < n_shards):
            raise ValueError(
                f"replica {self.replica_id}: shard {shard} is outside "
                f"the {n_shards}-shard assignment map"
            )
        old = self._client
        if old is not None:
            old.close()
            self._client = None
        from_tick = self.hydrated_tick
        if old is not None:
            from_tick = max(from_tick, old.applied_tick)
        prev_shard, prev_n = self.shard, self.n_shards
        self.shard, self.n_shards = shard, n_shards
        if self.store_root:
            # full re-partition: the snapshot holds the whole corpus,
            # hydrate() filters it to the NEW ownership (mmap — no wire)
            from_tick = self.hydrate()
        elif shard >= 0 and (
            prev_shard < 0
            or prev_n != n_shards
            or shard != prev_shard
        ):
            # store-less member: can only NARROW what it already holds
            # — a changed shard INDEX at the same count re-filters too
            # (serving the old range under the new label would hand
            # the router healthy-looking wrong answers); a merge that
            # needs foreign keys requires a store (or a restart
            # against the resharded writer's full replay)
            with self._index_lock:
                self._filter_to_shard(self.index)
        if self._has_stream:
            from pathway_tpu.parallel.replicate import DeltaStreamClient

            eps = self.writer_endpoints or [
                (self.writer_host, int(self.writer_port))
            ]
            self._client = DeltaStreamClient(
                eps[0][0],
                eps[0][1],
                self.replica_id,
                from_tick=from_tick,
                on_deltas=self._apply_deltas,
                on_resync=self._resync if self.store_root else None,
                on_applied=self._on_applied,
                shard=self.shard,
                expect_shards=self.n_shards if self.n_shards > 1 else 0,
                endpoints=eps,
            )
            self._client.start()
        import logging

        logging.getLogger("pathway_tpu").info(
            "replica %d: adopted shard map %s/%d (was %s/%d)",
            self.replica_id,
            shard,
            n_shards,
            prev_shard,
            prev_n,
        )
        # the reshard window's member-side edge in /fleet/events
        _journal_record(
            "shard-map-adopt",
            f"replica {self.replica_id} now owns shard {shard}/{n_shards} "
            f"(was {prev_shard}/{prev_n})",
            tick=from_tick,
            incarnation=self.incarnation,
            persist=True,
            replica_id=self.replica_id,
            shard=shard,
            n_shards=n_shards,
            prev_shard=prev_shard,
            prev_n_shards=prev_n,
        )

    def _apply_deltas(self, tick: int, batches: list) -> None:
        with self._index_lock:
            for b in batches:
                for k, d, vals in b.iter_rows():
                    if d > 0:
                        self.index.upsert(k, vals[0], vals[1])
                    else:
                        self.index.remove(k)

    def _on_applied(self, tick: int, n_applied: int) -> None:
        from pathway_tpu.testing import faults

        plan = faults.active()
        if plan is not None:
            plan.on_replica_tick(self.replica_id, n_applied)

    def search(self, triples: list) -> list:
        with self._index_lock:
            return self.index.search(triples)

    # --- serving ----------------------------------------------------------

    def corpus_stats(self) -> tuple[int, int]:
        """(resident docs, resident corpus bytes) — the per-member
        memory evidence the shard×replica sweep records (~1/S per
        member on a sharded plane)."""
        with self._index_lock:
            idx = self.index
            try:
                # O(1) — health is polled every PATHWAY_ROUTER_HEALTH_MS
                # under the same lock the query path takes, so never
                # materialize the key set here
                docs = len(idx)
            except TypeError:
                keys_fn = getattr(idx, "keys", None)
                docs = len(keys_fn()) if callable(keys_fn) else -1
            bytes_fn = getattr(idx, "resident_bytes", None)
            nbytes = int(bytes_fn()) if callable(bytes_fn) else -1
        return docs, nbytes

    def health(self) -> dict:
        c = self._client
        s = self.staleness_seconds()
        docs, nbytes = self.corpus_stats()
        gen = (
            self.generate_scheduler.stats()
            if self.generate_scheduler is not None
            else None
        )
        return {
            "generate": gen,
            "replica": self.replica_id,
            "incarnation": self.incarnation,
            "applied_tick": self.applied_tick,
            "newest_tick": c.newest_known if c is not None else -1,
            "staleness_seconds": s,
            "connected": bool(c.connected) if c is not None else False,
            "ready": self.ready,
            "stale": self.is_stale(),
            "inflight": self._inflight
            if self.admission is None
            else self.admission.inflight,
            "resyncs": c.resyncs if c is not None else 0,
            "hydrated_gen": self.hydrated_gen,
            "shard": self.shard,
            "n_shards": self.n_shards,
            "writer_incarnation": (
                c.writer_incarnation if c is not None else -1
            ),
            "fenced_writers": c.fenced_count if c is not None else 0,
            "config_error": c.config_error if c is not None else None,
            "corpus_docs": docs,
            "corpus_bytes": nbytes,
        }

    def _count(self, status: int) -> None:
        self._m_requests.labels(str(self.replica_id), str(status)).inc()


class _ReplicaHttp:
    """The replica's aiohttp front (own loop thread, PathwayWebserver
    pattern): POST <route> answers reads, GET /replica/health reports
    freshness for the router's poller."""

    def __init__(self, server: ReplicaServer):
        self.server = server
        self.port = server.http_port
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_ready = threading.Event()
        self._stop_async: Any = None
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopped = False
        self._bound = threading.Event()

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"pw-replica-http-{self.server.replica_id}",
        )
        self._thread.start()
        self._bound.wait(30.0)

    def _run(self) -> None:
        from aiohttp import web

        srv = self.server
        app = web.Application()

        async def handle_read(request: web.Request) -> web.Response:
            return await self._handle_read(request)

        async def handle_health(request: web.Request) -> web.Response:
            return web.json_response(srv.health())

        # Fleet Lens: the GET surfaces that make this replica a fleet
        # member — the router's /fleet/* federation scrapes these
        async def handle_metrics(request: web.Request) -> web.Response:
            from pathway_tpu.observability import REGISTRY

            return web.Response(
                text=REGISTRY.render(), content_type="text/plain"
            )

        async def handle_events(request: web.Request) -> web.Response:
            from pathway_tpu.observability.journal import journal

            j = journal()
            return web.json_response(
                {"member": j.member, "events": j.events()}
            )

        async def handle_signals(request: web.Request) -> web.Response:
            from pathway_tpu.observability.signals import get_sampler

            sampler = get_sampler()
            if sampler is None:
                return web.json_response(
                    {"enabled": False, "signals": {}, "slo": {}}
                )
            try:
                series = int(request.query.get("series", "0"))
            except ValueError:
                return web.json_response(
                    {"error": "series must be an integer"}, status=400
                )
            snap = sampler.snapshot(series_points=series)
            snap["enabled"] = True
            return web.json_response(snap)

        async def handle_trace(request: web.Request) -> web.Response:
            from pathway_tpu.observability.tracing import get_tracer as _gt

            try:
                seconds = float(request.query.get("seconds", "0"))
            except ValueError:
                return web.json_response(
                    {"error": "seconds must be a number"}, status=400
                )
            return web.json_response(
                _gt().chrome_trace(seconds=seconds if seconds > 0 else None)
            )

        app.router.add_post(srv.route, handle_read)
        app.router.add_get("/replica/health", handle_health)
        app.router.add_get("/metrics", handle_metrics)
        app.router.add_get("/debug/events", handle_events)
        app.router.add_get("/debug/signals", handle_signals)
        app.router.add_get("/debug/trace", handle_trace)
        for path, fn in srv.extra_post_routes.items():

            async def handle_extra(request: web.Request, _fn=fn):
                try:
                    resp = await _fn(self, request)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    # a handler bug must surface as a COUNTED
                    # structured 500 (the bench's error_served
                    # accounting reads these), never a raw aiohttp 500
                    # invisible to srv._count
                    resp = web.json_response(
                        {"error": f"{type(exc).__name__}: {exc}"},
                        status=500,
                    )
                # a streamed generation commits HTTP 200 at prepare;
                # its REAL outcome (e.g. a 504 mid-stream drop) rides
                # the override so request accounting stays honest
                srv._count(
                    getattr(resp, "_pathway_status_override", None)
                    or resp.status
                )
                return resp

            app.router.add_post(path, handle_extra)
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        stop_ev = asyncio.Event()
        self._stop_async = lambda: loop.call_soon_threadsafe(stop_ev.set)
        self._loop_ready.set()

        async def main():
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            await runner.setup()
            site = web.TCPSite(runner, srv.http_host, self.port)
            await site.start()
            self.port = runner.addresses[0][1] if runner.addresses else self.port
            self._bound.set()
            if not self._stopped:
                await stop_ev.wait()
            await runner.cleanup()

        try:
            loop.run_until_complete(main())
        finally:
            self._bound.set()
            loop.close()

    async def _handle_read(self, request):
        from aiohttp import web

        from pathway_tpu.observability import tracing

        srv = self.server
        span = tracing.get_tracer().span(
            "replica.request",
            parent=tracing.parse_traceparent(
                request.headers.get("traceparent")
            ),
            root=True,
            replica=srv.replica_id,
            route=srv.route,
        )
        with span:
            status, payload, headers = await self._serve(request)
            span.set_attribute("status", status)
        srv._count(status)
        if span.context is not None:
            headers["traceparent"] = span.context.traceparent()
        return web.json_response(payload, status=status, headers=headers)

    async def _serve(self, request) -> tuple[int, Any, dict]:
        from pathway_tpu.serving.admission import ShedError

        srv = self.server
        staleness = srv.staleness_seconds()
        stale = srv.is_stale()
        headers = {
            "x-pathway-replica": str(srv.replica_id),
            "x-pathway-applied-tick": str(srv.applied_tick),
            "x-pathway-staleness-seconds": (
                f"{staleness:.3f}" if staleness is not None else "unknown"
            ),
        }
        if stale:
            headers["x-pathway-stale"] = "true"
        # the request's freshness bound: shed explicitly rather than
        # silently serve data older than the client can accept
        if staleness_bound_exceeded(
            staleness,
            stale,
            request.headers.get("x-pathway-max-staleness-ms"),
        ):
            return (
                503,
                {
                    "error": "replica staler than "
                    "x-pathway-max-staleness-ms",
                    "replica": srv.replica_id,
                },
                {"Retry-After": "1.0", **headers},
            )
        tenant = request.headers.get("x-pathway-tenant")
        tenant_class = request.headers.get("x-pathway-tenant-class")
        if srv.admission is not None:
            try:
                srv.admission.admit(
                    tenant=tenant, tenant_class=tenant_class
                )
            except ShedError as e:
                return (
                    e.status,
                    {"error": f"request shed: {e.reason}"},
                    {"Retry-After": f"{e.retry_after_s:.3f}", **headers},
                )
        else:
            with srv._inflight_lock:
                srv._inflight += 1
        try:
            try:
                values = await request.json()
            except ValueError:
                values = {}
            loop = asyncio.get_running_loop()
            payload = await loop.run_in_executor(
                None, srv.responder, srv, values
            )
            if srv.tenant_ledger is not None:
                srv.tenant_ledger.observe_staleness(tenant, staleness)
            return 200, payload, headers
        except Exception as exc:
            return (
                500,
                {"error": f"{type(exc).__name__}: {exc}"},
                headers,
            )
        finally:
            if srv.admission is not None:
                srv.admission.on_flushed(1)
                srv.admission.complete()
            else:
                with srv._inflight_lock:
                    srv._inflight -= 1

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started or self._stopped:
            return
        self._stopped = True
        self._loop_ready.wait(timeout)
        stop_async = self._stop_async
        if stop_async is not None:
            try:
                stop_async()
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout)


def main() -> int:
    """Env-configured KNN replica — the subprocess role the chaos bench
    and the multi-process failover tests spawn (usually under the
    Phoenix Mesh supervisor for restart-on-kill):

    PATHWAY_REPLICA_ID        this replica's id (default 0)
    PATHWAY_REPLICA_STORE     writer's persistence root (hydration)
    PATHWAY_REPL_PORT         writer's delta-stream port
    PATHWAY_REPL_WRITER_HOST  writer host (default 127.0.0.1)
    PATHWAY_REPL_STANDBY      optional standby endpoint "host:port"
                              appended to the dial list (takeover)
    PATHWAY_REPLICA_HTTP_PORT HTTP port (default 0 = ephemeral)
    PATHWAY_REPLICA_DIM       vector dimensionality (default 32)
    PATHWAY_REPLICA_ROUTE     read route (default /query)
    PATHWAY_SERVING_SHARDS    total corpus shards (default 1)
    PATHWAY_REPLICA_SHARD     the shard this member owns (required
                              when PATHWAY_SERVING_SHARDS > 1)

    Prints ``REPLICA-READY <http_port>`` once serving, then runs until
    SIGTERM.  Exit code 0 on clean termination; Fault-Forge kills exit
    with FAULT_EXIT (23) like every injected death.
    """
    import signal
    import sys

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # same guard as bench.py: under the axon sitecustomize the env
        # route still initializes the tunneled backend; config.update
        # does not
        import jax

        jax.config.update("jax_platforms", "cpu")
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    # the replica's Surge-Gate admission (its serving-capacity
    # envelope): PATHWAY_SERVING_ENABLED=1 + the standard
    # PATHWAY_SERVING_* knobs (RPS/BURST/MAX_INFLIGHT...) bound each
    # replica exactly like a gated writer endpoint — the router
    # balances IN FRONT of these gates
    from pathway_tpu.serving import QoSConfig, serving_enabled_via_env

    qos = QoSConfig.from_env() if serving_enabled_via_env() else None
    dim = int(os.environ.get("PATHWAY_REPLICA_DIM", "32") or 32)
    writer_port_raw = os.environ.get("PATHWAY_REPL_PORT", "")
    writer_host = os.environ.get("PATHWAY_REPL_WRITER_HOST", "127.0.0.1")
    endpoints: list[tuple[str, int]] | None = None
    standby_raw = os.environ.get("PATHWAY_REPL_STANDBY", "")
    if writer_port_raw and standby_raw:
        host, _, port = standby_raw.rpartition(":")
        endpoints = [
            (writer_host, int(writer_port_raw)),
            (host or writer_host, int(port)),
        ]
    from pathway_tpu.parallel.replicate import shards_env

    n_shards = shards_env()
    shard_raw = os.environ.get("PATHWAY_REPLICA_SHARD", "")
    server = ReplicaServer(
        replica_id=int(os.environ.get("PATHWAY_REPLICA_ID", "0") or 0),
        index_factory=lambda: TpuDenseKnnIndex(dimensions=dim),
        store_root=os.environ.get("PATHWAY_REPLICA_STORE") or None,
        writer_host=writer_host,
        writer_port=int(writer_port_raw) if writer_port_raw else None,
        writer_endpoints=endpoints,
        http_port=int(
            os.environ.get("PATHWAY_REPLICA_HTTP_PORT", "0") or 0
        ),
        route=os.environ.get("PATHWAY_REPLICA_ROUTE", "/query"),
        qos=qos,
        dim=dim,
        shard=int(shard_raw) if shard_raw else -1,
        n_shards=n_shards,
    )
    # Token Loom: PATHWAY_GENERATE=1 mounts the /generate route (the
    # ask->retrieve->generate stage) on this replica, configured by the
    # PATHWAY_GENERATE_* knobs (pool size, snapshot cadence, store)
    from pathway_tpu.generate.scheduler import generate_enabled_via_env

    if generate_enabled_via_env():
        from pathway_tpu.generate.serving import attach_generate

        attach_generate(server)
    # Fleet Lens: the subprocess replica role samples its own SLO
    # signals (served at /debug/signals) and writes a postmortem bundle
    # on unhandled exceptions — both opt-out via PATHWAY_SIGNALS=0 /
    # unset PATHWAY_POSTMORTEM_DIR
    from pathway_tpu.observability.journal import install_crash_hooks
    from pathway_tpu.observability.signals import arm_sampler

    arm_sampler()
    install_crash_hooks()
    server.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())
    print(f"REPLICA-READY {server.http_port}", flush=True)
    while not stop.is_set():
        stop.wait(0.2)
    server.stop()
    print("REPLICA-CLEAN-EXIT", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
