"""SurgeGate — the serving QoS layer between REST ingress and the
engine tick.

One gate per rest_connector endpoint. The aiohttp handler builds a
``PendingRequest`` and calls ``submit``: admission control (bounded
queue, per-endpoint concurrency cap, token-bucket rate limit) may shed
it with an explicit 429/503 + Retry-After; otherwise it joins the
micro-batcher's EDF queue and, at flush, the whole release is inserted
atomically into the endpoint's ``InputSession`` so a single engine tick
(and a single jitted embed/KNN batch) carries it. ``drain`` stops
admission, flushes in-flight batches, waits for every admitted request
to finish, and then the webserver can shut down cleanly.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any

from pathway_tpu.serving import deadline as _deadline
from pathway_tpu.serving import metrics as _metrics
from pathway_tpu.serving.admission import (
    AdmissionController,
    DeadlineExceeded,
    ShedError,
)
from pathway_tpu.serving.batcher import MicroBatcher
from pathway_tpu.serving.config import QoSConfig

# all live gates of this process (drain_all / debug); weak so cleared
# graphs release their gates without an explicit unregister
_GATES: "weakref.WeakSet[SurgeGate]" = weakref.WeakSet()
_GATES_LOCK = threading.Lock()

INTERACTIVE_PRIORITY = 0  # InputSession.priority value for gated queries

# PendingRequest lifecycle. The handler's teardown (client may have
# disconnected while the request sat in the batcher queue) and the
# batcher's flush race on the same request from two threads; the state
# transition decides, atomically, whether the request reaches the
# engine (DISPATCHED) or is forgotten (ABANDONED) — never both.
_PENDING = 0
_DISPATCHED = 1
_ABANDONED = 2


class PendingRequest:
    """One admitted-or-not REST request crossing the gate."""

    __slots__ = (
        "key",
        "vals",
        "deadline",
        "enqueued_at",
        "loop",
        "dispatched",
        "tenant",
        "tenant_class",
        "order",
        "_state",
        "_state_lock",
    )

    def __init__(
        self,
        key: int,
        vals: tuple,
        deadline: float,
        loop: Any = None,
        dispatched: Any = None,
        tenant: str | None = None,
        tenant_class: str | None = None,
    ):
        self.key = key
        self.vals = vals
        self.deadline = float(deadline)
        self.enqueued_at = time.monotonic()
        # Tenant Weave: identity from the x-pathway-tenant header and
        # the batcher's order key — plain EDF (the deadline) unless the
        # route's ledger stamps a weighted-fair (vfinish, deadline) tag
        self.tenant = tenant
        self.tenant_class = tenant_class
        self.order: Any = self.deadline
        # asyncio plumbing: `dispatched` resolves (with the batch size)
        # when the micro-batcher releases the request into the engine,
        # or errors with DeadlineExceeded/ShedError when it is dropped
        self.loop = loop
        self.dispatched = dispatched
        self._state = _PENDING
        self._state_lock = threading.Lock()

    @property
    def was_dispatched(self) -> bool:
        return self._state == _DISPATCHED

    def try_mark_dispatched(self) -> bool:
        """Batcher side: claim the request for dispatch. False means
        the handler abandoned it — skip it entirely."""
        with self._state_lock:
            if self._state == _ABANDONED:
                return False
            self._state = _DISPATCHED
            return True

    def abandon(self) -> bool:
        """Handler side: True iff the request never reached (and now
        never will reach) the engine, so the handler owes no
        dispatch-window slot; False = it was dispatched."""
        with self._state_lock:
            if self._state == _DISPATCHED:
                return False
            self._state = _ABANDONED
            return True

    def resolve_dispatched(self, batch_size: int) -> None:
        if self.loop is None or self.dispatched is None:
            return
        fut = self.dispatched

        def _set() -> None:
            if not fut.done():
                fut.set_result(batch_size)

        try:
            self.loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # loop already closed (server shutting down)

    def reject(self, exc: BaseException) -> None:
        if self.loop is None or self.dispatched is None:
            return
        fut = self.dispatched

        def _set() -> None:
            if not fut.done():
                fut.set_exception(exc)

        try:
            self.loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass


class SurgeGate:
    def __init__(
        self,
        config: QoSConfig,
        session: Any,
        route: str = "/",
        webserver: Any = None,
    ):
        self.config = config
        self.session = session
        self.route = route
        self.webserver = webserver
        self.admission = AdmissionController(config, route)
        self._m_wait = _metrics.queue_wait_histogram().labels(route)
        self._m_batch_rows = _metrics.batch_rows_histogram().labels(route)
        self._m_occupancy = _metrics.occupancy_histogram()
        self._m_expired = _metrics.expired_counter().labels("gate")
        self._closed = False
        # Tenant Weave (PATHWAY_TENANT_QOS=1): per-tenant fair-share
        # buckets + weighted-fair EDF ordering + queue-full eviction
        # that charges the hot tenant.  None = tenant-blind plane,
        # byte-identical to the pre-tenancy gate.
        from pathway_tpu.serving import tenancy as _tenancy

        self.ledger = _tenancy.ledger_for(config, route)
        # dispatch window: requests released into the engine but whose
        # response has not gone out yet; the batcher holds further
        # releases while the window is full so overload accumulates in
        # the bounded admission queue, not the InputSession
        self._disp_lock = threading.Lock()
        self._dispatched_pending = 0
        self.batcher = _make_batcher(self)
        if getattr(session, "priority", None) is not None and (
            config.priority == "interactive"
        ):
            session.priority = INTERACTIVE_PRIORITY
            # the scheduler's hot-check: queries waiting in the batcher
            # are about to land in this session, so bulk sessions should
            # already be deferring (session.has_data() alone only sees
            # rows AFTER a flush). Closes over the admission controller,
            # not the gate — sessions outlive runs (G.last_runtime) and
            # must not pin the gate (and its batcher thread) with them.
            admission = self.admission
            session.backlog = lambda: admission.queued
        with _GATES_LOCK:
            _GATES.add(self)

    # --- ingress ----------------------------------------------------------

    def submit(self, req: PendingRequest) -> None:
        """Admit + enqueue. Raises ShedError (shed with a status and a
        Retry-After) or DeadlineExceeded (budget already spent)."""
        now = time.monotonic()
        if req.deadline <= now:
            self._m_expired.inc()
            raise DeadlineExceeded()
        if self.ledger is not None:
            # per-tenant fair share (shed charged to the hot tenant)
            # + the weighted-fair EDF tag the batcher orders on
            tag = self.ledger.admit(
                req.tenant,
                req.tenant_class,
                now,
                pressure=self.admission.under_pressure(now),
            )
            req.order = (tag, req.deadline)
            if (
                self.admission.queued >= self.config.max_queue
                # only when the queue is the SOLE binding constraint:
                # evicting a queued request for an arrival the bucket
                # or concurrency cap would shed anyway loses both
                and self.admission.headroom_besides_queue(now)
            ):
                # full queue: evict the MOST over-share tenant's queued
                # request instead of shedding this arrival — the shed
                # lands on the noisy neighbor, never the queue tail.
                # (If the arrival itself is the hottest, pick_victim
                # returns None and the normal queue_full shed applies.)
                victim = self.batcher.steal(
                    lambda queued: self.ledger.pick_victim(queued, tag)
                )
                if victim is not None:
                    self.ledger.count_evicted(victim.tenant)
                    self._reject(
                        victim,
                        ShedError(
                            429,
                            "tenant_evict",
                            max(self.config.max_wait_ms / 1000.0, 0.05),
                        ),
                    )
        try:
            self.admission.admit(now)
        except ShedError:
            if self.ledger is not None:
                # shed on the SHARED path: the request never entered
                # the queue, so the tenant's fair-share charge comes
                # back — retrying into a full queue must not drain the
                # tenant's own budget (see TenantLedger.refund)
                self.ledger.refund(req.tenant, req.tenant_class, tag)
            raise
        if self.ledger is not None:
            self.ledger.commit(req.tenant)
        req.enqueued_at = now
        _deadline.register(req.key, req.deadline)
        try:
            self.batcher.put(req)
        except RuntimeError:
            # the request never entered the queue: undo BOTH admission
            # counters (admit bumped queued and inflight)
            _deadline.unregister(req.key)
            self.admission.on_flushed(1)
            self.admission.complete()
            raise ShedError(503, "shutdown", 1.0) from None

    def complete(
        self, key: int | None = None, was_dispatched: bool = False
    ) -> None:
        """The response for an admitted request went out (any status)."""
        if key is not None:
            _deadline.unregister(key)
        self.admission.complete()
        if was_dispatched:
            with self._disp_lock:
                self._dispatched_pending = max(
                    0, self._dispatched_pending - 1
                )
            self.batcher.notify()

    def _dispatch_capacity(self) -> int:
        with self._disp_lock:
            return self.config.dispatch_window() - self._dispatched_pending

    # --- batcher callbacks (batcher thread) -------------------------------

    def _dispatch(self, reqs: list) -> None:
        now = time.monotonic()
        # window slots are claimed for the WHOLE batch before any
        # request is marked dispatched: a handler releases its slot
        # only after try_mark_dispatched flipped the state, so the
        # release can never run ahead of this increment and be clamped
        # away (which would leak the slot and wedge the gate); if the
        # insert below raises, the handlers still observe
        # was_dispatched and release their slots in complete()
        with self._disp_lock:
            self._dispatched_pending += len(reqs)
        # claim each request atomically: a handler whose client went
        # away while the request sat in the queue marked it abandoned —
        # it must not burn an engine batch slot, and its window slots
        # are returned right here (nobody else will)
        live = [r for r in reqs if r.try_mark_dispatched()]
        n = len(live)
        if n < len(reqs):
            with self._disp_lock:
                self._dispatched_pending = max(
                    0, self._dispatched_pending - (len(reqs) - n)
                )
        if n:
            self.session.insert_batch([(r.key, 1, r.vals) for r in live])
            self._m_batch_rows.observe(n)
            bucket = self.config.bucket_for(n)
            self._m_occupancy.labels("gate", str(bucket)).observe(
                min(1.0, n / bucket)
            )
            ledger = self.ledger
            for r in live:
                wait = max(0.0, now - r.enqueued_at)
                self._m_wait.observe(wait)
                if ledger is not None:
                    ledger.observe_wait(r.tenant, wait)
                    ledger.note_dispatched(r.order)
                r.resolve_dispatched(n)
        # counted LAST: if anything above raised, the batcher's
        # catch-all _rejects every request and _reject does its own
        # on_flushed — counting here too would double-decrement queued
        self.admission.on_flushed(len(reqs))

    def _reject(self, req: Any, exc: BaseException) -> None:
        self.admission.on_flushed(1)
        if isinstance(exc, DeadlineExceeded):
            self._m_expired.inc()
        req.reject(exc)

    # --- lifecycle --------------------------------------------------------

    def drain(self, grace_s: float | None = None) -> bool:
        """Stop admitting (503 + Retry-After), flush everything queued,
        then wait for every admitted request's response. Returns True if
        the gate went fully idle within the grace period."""
        if grace_s is None:
            grace_s = self.config.drain_grace_s
        self.admission.start_drain()
        self.batcher.drain()
        return self.admission.wait_idle(grace_s)

    def close(self) -> None:
        """Hard stop: queued-but-undispatched requests fail with 503."""
        if self._closed:
            return
        self._closed = True
        self.admission.start_drain()
        self.batcher.close(reject_queued=ShedError(503, "shutdown", 1.0))

    @property
    def queue_depth(self) -> int:
        return self.admission.queued

    @property
    def inflight(self) -> int:
        return self.admission.inflight


def _make_batcher(gate: SurgeGate) -> MicroBatcher:
    """Wire the batcher callbacks through a weakref so the daemon flush
    thread never keeps the gate alive: a graph torn down without an
    explicit stop lets the gate (and its metric callbacks) be
    collected, at which point the finalizer closes the thread instead
    of leaking one per endpoint."""
    ref = weakref.ref(gate)
    config = gate.config

    def dispatch(reqs: list) -> None:
        g = ref()
        if g is None:
            raise RuntimeError("gate collected")
        g._dispatch(reqs)

    def reject(req: Any, exc: BaseException) -> None:
        g = ref()
        if g is None:
            req.reject(exc)
        else:
            g._reject(req, exc)

    def capacity() -> int:
        g = ref()
        return config.max_batch_size if g is None else g._dispatch_capacity()

    batcher = MicroBatcher(
        config,
        dispatch=dispatch,
        reject=reject,
        capacity=capacity,
        name=f"surge-gate{gate.route.replace('/', '-')}",
        # weighted-fair EDF only when a tenant ledger stamped the order
        # tag; None keeps the batcher's plain-EDF default path
        order=(None if gate.ledger is None else (lambda r: r.order)),
    )
    weakref.finalize(gate, batcher.close)
    return batcher


def gates() -> list[SurgeGate]:
    with _GATES_LOCK:
        return list(_GATES)


def drain_all(
    grace_s: float | None = None, stop_webservers: bool = True
) -> bool:
    """Drain every live gate (stop admitting, flush, wait for in-flight
    responses) and then stop their webservers. Returns True when every
    gate went idle within its grace period."""
    all_idle = True
    current = gates()
    for gate in current:
        all_idle = gate.drain(grace_s) and all_idle
    for gate in current:
        gate.close()
    if stop_webservers:
        seen: set[int] = set()
        for gate in current:
            ws = gate.webserver
            if ws is None or id(ws) in seen:
                continue
            seen.add(id(ws))
            try:
                ws.stop()
            except Exception:
                pass
    return all_idle
