"""pw.io.airbyte — run an Airbyte source connector and ingest its record
stream (reference: python/pathway/io/airbyte — drives an Airbyte
connector image/venv through the Airbyte protocol: spec/check/read over
stdout JSON lines). This implementation shells out to a locally installed
connector executable (`docker run` or a venv entrypoint) and parses
RECORD/STATE messages."""

from __future__ import annotations

import json as _json
import subprocess
import threading
from typing import Any

from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import sequential_key
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class _AirbyteSource(StreamingSource):  # pragma: no cover - needs connector
    def __init__(self, command: list[str], streams: list[str]):
        super().__init__(["data"])
        self.command = command
        self.streams = set(streams)
        self._stop = threading.Event()
        self._thread = None
        self._counter = 0

    def _loop(self):
        proc = subprocess.Popen(
            self.command, stdout=subprocess.PIPE, text=True
        )
        assert proc.stdout is not None
        for line in proc.stdout:
            if self._stop.is_set():
                proc.terminate()
                break
            line = line.strip()
            if not line:
                continue
            try:
                msg = _json.loads(line)
            except ValueError:
                continue
            if msg.get("type") == "RECORD":
                rec = msg.get("record", {})
                if self.streams and rec.get("stream") not in self.streams:
                    continue
                self._counter += 1
                self.session.insert(
                    int(sequential_key(self._counter)),
                    (Json(rec.get("data")),),
                )
        self.session.close()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


def read(
    config: dict | str,
    streams: list[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    env_vars: dict | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    if isinstance(config, dict):
        command = config.get("command")
        if not command:
            raise ValueError(
                "pw.io.airbyte needs {'command': [...]} pointing at a local "
                "Airbyte connector executable (docker run ... read ...)"
            )
    else:
        command = [config]
    source = _AirbyteSource(list(command), streams)
    node = InputNode(source, source.column_names)
    return Table._from_node(node, {"data": dt.JSON}, Universe())
