"""pw.io.pyfilesystem — read from any fsspec/PyFilesystem-style source
(reference: python/pathway/io/pyfilesystem — reads binary objects from a
PyFilesystem FS object). Accepts either an fsspec filesystem or a
PyFilesystem2 FS (duck-typed: needs listdir/open or find/open)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class _PyFsSource(StaticSource):
    def __init__(self, source, path):
        super().__init__(["data", "path"])
        self.fs = source
        self.path = path

    def _list(self) -> list[str]:
        if hasattr(self.fs, "find"):  # fsspec
            return sorted(self.fs.find(self.path))
        if hasattr(self.fs, "walk"):  # pyfilesystem2
            return sorted(
                p.path if hasattr(p, "path") else str(p)
                for p in self.fs.walk.files(self.path or "/")
            )
        raise TypeError("unsupported filesystem object")

    def _read(self, p: str) -> bytes:
        if hasattr(self.fs, "open"):
            mode = "rb"
            with self.fs.open(p, mode) as f:
                return f.read()
        raise TypeError("unsupported filesystem object")

    def events(self):
        rows = []
        for p in self._list():
            data = self._read(p)
            rows.append((int(ref_scalar(p)), 1, (data, p)))
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


def read(
    source: Any,
    path: str = "",
    *,
    mode: str = "static",
    with_metadata: bool = False,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    src = _PyFsSource(source, path)
    node = InputNode(src, src.column_names)
    return Table._from_node(
        node, {"data": dt.BYTES, "path": dt.STR}, Universe()
    )
