"""pw.io.sharepoint — SharePoint source stub.

The reference gates the real implementation behind its enterprise
offering (reference: python/pathway/xpacks/connectors/sharepoint — OSS
tree ships a stub raising at call time); this mirrors that surface."""

from __future__ import annotations

from typing import Any


def read(*args: Any, **kwargs: Any):
    raise NotImplementedError(
        "pw.io.sharepoint is not available in this build (the reference "
        "gates it behind an enterprise license; use pw.io.fs / pw.io.s3 "
        "with a synced drive instead)"
    )
