"""pw.io.slack — alert sink posting rows to a Slack channel
(reference: python/pathway/io/slack — send_alerts via chat.postMessage)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, jsonable


def send_alerts(
    alerts: Any, slack_channel_id: str, slack_token: str, **kwargs: Any
) -> None:
    """Post the first column of every inserted row as a Slack message."""
    import requests

    session = requests.Session()
    session.headers["Authorization"] = f"Bearer {slack_token}"

    def on_batch(t: int, batch: DiffBatch) -> None:
        col = batch.column_names[0] if batch.column_names else None
        for _k, d, vals in batch.iter_rows():
            if d <= 0:
                continue
            text = str(jsonable(vals[0])) if col is not None else ""
            resp = session.post(
                "https://slack.com/api/chat.postMessage",
                json={"channel": slack_channel_id, "text": text},
                timeout=30,
            )
            resp.raise_for_status()

    add_writer(alerts, on_batch)
