"""pw.io.logstash — sink for the Logstash HTTP input plugin
(reference: python/pathway/io/logstash — forwards rows over HTTP)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, row_dicts


def write(table, endpoint: str, n_retries: int = 0, **kwargs: Any) -> None:
    import time

    import requests

    column_names = table.column_names()
    session = requests.Session()

    def on_batch(t: int, batch: DiffBatch) -> None:
        for _k, d, doc in row_dicts(batch, column_names, t):
            doc["diff"] = d
            doc["time"] = t
            for attempt in range(n_retries + 1):
                try:
                    resp = session.post(endpoint, json=doc, timeout=30)
                    resp.raise_for_status()
                    break
                except requests.RequestException:
                    if attempt == n_retries:
                        raise
                    time.sleep(min(2**attempt * 0.1, 5.0))

    add_writer(table, on_batch)
