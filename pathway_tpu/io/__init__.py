"""pw.io — connector façade (reference: python/pathway/io/__init__.py:35-67,
28 modules). Local/file/python/http connectors are native here; cloud-service
connectors (kafka, s3, ...) share the same reader/writer framework."""

from __future__ import annotations

from pathway_tpu.io import csv, fs, jsonlines, plaintext, python
from pathway_tpu.io._subscribe import subscribe

from pathway_tpu.io._subscribe import (  # noqa: F401
    OnChangeCallback,
    OnFinishCallback,
)
from pathway_tpu.io.csv import CsvParserSettings  # noqa: F401

__all__ = [
    "CsvParserSettings",
    "OnChangeCallback",
    "OnFinishCallback",
    "csv",
    "fs",
    "jsonlines",
    "plaintext",
    "python",
    "subscribe",
    "http",
]


def __getattr__(name: str):
    # lazily import heavier / optional connector modules
    import importlib

    known = {
        "http",
        "kafka",
        "redpanda",
        "debezium",
        "postgres",
        "elasticsearch",
        "mongodb",
        "nats",
        "sqlite",
        "deltalake",
        "iceberg",
        "bigquery",
        "pubsub",
        "gdrive",
        "s3",
        "s3_csv",
        "minio",
        "airbyte",
        "null",
        "slack",
        "logstash",
        "pyfilesystem",
        "sharepoint",
    }
    if name in known:
        return importlib.import_module(f"pathway_tpu.io.{name}")
    raise AttributeError(name)
