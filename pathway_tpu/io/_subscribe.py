"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import OutputNode
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import Pointer
from pathway_tpu.internals.table import Table


# callback type aliases (reference: io/_subscribe.py)
OnChangeCallback = Callable[..., None]
OnFinishCallback = Callable[[], None]


def subscribe(
    table: Table,
    on_change: Callable[..., Any],
    on_end: Callable[[], Any] | None = None,
    on_time_end: Callable[[int], Any] | None = None,
    *,
    skip_persisted_batch: bool = True,
    name: str | None = None,
    sort_by: Any = None,
) -> None:
    """Call ``on_change(key, row, time, is_addition)`` for every change."""
    col_names = table.column_names()

    def on_batch(t: int, batch: DiffBatch) -> None:
        from pathway_tpu.internals.api import Error

        for k, d, vals in batch.iter_rows():
            if any(isinstance(v, Error) for v in vals):
                # reference: output connectors skip rows carrying Error
                # values (the error is already in the log)
                continue
            row = dict(zip(col_names, vals))
            on_change(key=Pointer(k), row=row, time=t, is_addition=d > 0)
        if on_time_end is not None:
            on_time_end(t)

    def end() -> None:
        if on_end is not None:
            on_end()

    node = OutputNode(table._node, on_batch, end)
    parse_graph.G.add_output(node)
