"""pw.io.deltalake — Delta Lake source/sink on pyarrow.

TPU-native counterpart of the reference's DeltaLake connector
(reference: src/connectors/data_lake/{mod,delta,writer}.rs — arrow-based
batch/streaming readers and transactional writers, 2k LoC of rust). The
image has pyarrow but no `deltalake` package, so this implements the core
of the Delta protocol directly:

- parquet part files + an ordered `_delta_log/` of JSON commits holding
  `add` / `remove` actions; readers REPLAY the log, so overwrites and
  compactions are honored (removed files drop out of the active set);
- transactional commits: parquet written first, then the commit file is
  created EXCLUSIVELY (optimistic concurrency — a concurrent writer's
  version collision is detected and retried at the next version, the
  delta commit protocol, reference writer.rs). The exclusive-create
  guarantee holds on LOCAL filesystems (hard-link atomicity); plain
  object stores lack conditional puts, so concurrent multi-writer use
  over s3:// needs external coordination (same caveat as delta-rs
  without a locking provider);
- schema tracked in `metaData` actions with evolution guards: appending
  writers must match the table schema; adding new columns is allowed
  with ``schema_evolution="allow_add"`` (a new metaData action is
  committed), type changes/drops are rejected;
- object storage: any fsspec URI (s3://bucket/table, memory://...)
  works through the same code path as local directories (reference:
  data_lake S3 object store over rust-s3);
- maintenance: ``compact_every=N`` merges the active part files into one
  parquet every N commits (remove+add in a single commit — the
  reference's table maintenance/optimize pass);
- streaming reads tail the log and emit RETRACTIONS for rows of removed
  files, so a downstream incremental pipeline tracks overwrites.

Output rows carry `time`/`diff` columns like the reference writer.
"""

from __future__ import annotations

import json as _json
import os
import threading
import uuid
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable

_LOG_DIR = "_delta_log"


def create_exclusive_local(path: str, data: bytes) -> bool:
    """Atomically create `path` iff it does not exist (hard-link trick) —
    the optimistic-commit primitive shared by the delta and iceberg
    writers. Returns False on collision."""
    tmp = path + f".tmp-{uuid.uuid4().hex}"
    with open(tmp, "wb") as f:
        f.write(data)
    try:
        os.link(tmp, path)
        return True
    except FileExistsError:
        return False
    finally:
        os.remove(tmp)


class _Store:
    """Filesystem facade: plain os for local paths, fsspec for URIs with a
    scheme (s3://, memory://, ...). Only the handful of operations the
    Delta log needs."""

    def __init__(self, root: str, storage_options: dict | None = None):
        self.root = root.rstrip("/")
        if "://" in root:
            import fsspec

            self.protocol = root.split("://", 1)[0]
            self.fs = fsspec.filesystem(
                self.protocol, **(storage_options or {})
            )
            self._local = False
        else:
            self.fs = None
            self._local = True

    def join(self, *parts: str) -> str:
        if self._local:
            return os.path.join(self.root, *parts)
        return "/".join([self.root, *parts])

    def makedirs(self, path: str) -> None:
        if self._local:
            os.makedirs(path, exist_ok=True)
        else:
            self.fs.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> list[str]:
        try:
            if self._local:
                return os.listdir(path)
            return [p.rsplit("/", 1)[-1] for p in self.fs.ls(path, detail=False)]
        except (OSError, FileNotFoundError):
            return []

    def read_text(self, path: str) -> str:
        if self._local:
            with open(path) as f:
                return f.read()
        with self.fs.open(path, "r") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        if self._local:
            with open(path, "wb") as f:
                f.write(data)
        else:
            with self.fs.open(path, "wb") as f:
                f.write(data)

    def open_read(self, path: str):
        if self._local:
            return open(path, "rb")
        return self.fs.open(path, "rb")

    def size(self, path: str) -> int:
        if self._local:
            return os.path.getsize(path)
        return self.fs.size(path)

    def remove(self, path: str) -> None:
        try:
            if self._local:
                os.remove(path)
            else:
                self.fs.rm(path)
        except (OSError, FileNotFoundError):
            pass

    def create_exclusive(self, path: str, data: bytes) -> bool:
        """Atomically create `path` iff it does not exist — the delta
        optimistic-commit primitive. Returns False on collision."""
        if self._local:
            return create_exclusive_local(path, data)
        if self.fs.exists(path):
            return False
        with self.fs.open(path, "wb") as f:  # best-effort on object stores
            f.write(data)
        return True


def _log_path(store: _Store, version: int) -> str:
    return store.join(_LOG_DIR, f"{version:020d}.json")


def _list_versions(store: _Store) -> list[int]:
    out = []
    for f in store.listdir(store.join(_LOG_DIR)):
        if f.endswith(".json"):
            try:
                out.append(int(f[:-5]))
            except ValueError:
                pass
    return sorted(out)


def _version_actions(store: _Store, version: int) -> list[dict]:
    actions = []
    for line in store.read_text(_log_path(store, version)).splitlines():
        line = line.strip()
        if line:
            actions.append(_json.loads(line))
    return actions


def _replay_log(
    store: _Store, upto: int | None = None
) -> tuple[list[str], dict | None]:
    """(active part files in add order, latest schema) after replaying the
    log — `remove` actions drop files from the active set."""
    active: dict[str, None] = {}
    schema = None
    for v in _list_versions(store):
        if upto is not None and v > upto:
            break
        for action in _version_actions(store, v):
            if "add" in action:
                active[action["add"]["path"]] = None
            elif "remove" in action:
                active.pop(action["remove"]["path"], None)
            elif "metaData" in action:
                try:
                    schema = _json.loads(
                        action["metaData"].get("schemaString", "null")
                    )
                except (ValueError, TypeError):
                    schema = None
    return list(active.keys()), schema


def _rows_from_parquet(
    source, column_names, schema, counter
) -> list[tuple[int, int, tuple]]:
    """`source` is a filesystem path or an open binary file — pyarrow
    accepts both (iceberg passes local paths; delta passes _Store file
    handles so object stores work)."""
    import pyarrow.parquet as pq

    tbl = pq.read_table(source)
    data = tbl.to_pylist()
    dtypes = schema.dtypes() if schema else {}
    pk = schema.primary_key_columns() if schema else None
    rows = []
    for obj in data:
        vals = []
        for c in column_names:
            v = obj.get(c)
            d = dtypes.get(c, dt.ANY).strip_optional()
            if d == dt.FLOAT and isinstance(v, int):
                v = float(v)
            vals.append(v)
        vals = tuple(vals)
        diff = int(obj.get("diff", 1))
        if pk:
            key = int(ref_scalar(*[vals[column_names.index(c)] for c in pk]))
        else:
            # key by value content so a -1 row cancels its earlier +1 even
            # without a declared primary key (sequential keys would orphan
            # retractions); identical duplicates coexist via multiplicity
            key = int(ref_scalar(*vals))
        rows.append((key, diff, vals))
    return rows


class _DeltaStaticSource(StaticSource):
    def __init__(self, store: _Store, column_names, schema):
        super().__init__(column_names)
        self.store = store
        self.schema = schema

    def events(self):
        import itertools

        counter = itertools.count()
        rows = []
        files, _meta = _replay_log(self.store)
        for part in files:
            with self.store.open_read(self.store.join(part)) as f:
                rows.extend(
                    _rows_from_parquet(
                        f, self.column_names, self.schema, counter
                    )
                )
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _DeltaStreamingSource(StreamingSource):
    """Tail the log; `add` emits the file's rows, `remove` (overwrite /
    compaction) retracts them — downstream pipelines see overwrites as
    incremental updates."""

    def __init__(self, store: _Store, column_names, schema, refresh_s=0.2):
        super().__init__(column_names)
        self.store = store
        self.schema = schema
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread = None
        self._next_version = 0
        # part path -> rows it contributed (for retraction on remove)
        self._live: dict[str, list] = {}
        import itertools

        self._counter = itertools.count()

    def offset_state(self) -> dict:
        return {"next_version": self._next_version}

    def seek(self, state: dict) -> None:
        self._next_version = int(state.get("next_version", 0))
        # rebuild the live map WITHOUT emitting (those rows were already
        # delivered before the restart; the input log replays them)
        files, _meta = _replay_log(self.store, upto=self._next_version - 1)
        for part in files:
            try:
                with self.store.open_read(self.store.join(part)) as f:
                    self._live[part] = _rows_from_parquet(
                        f, self.column_names, self.schema, self._counter
                    )
            except OSError:
                pass

    def _scan(self):
        for v in _list_versions(self.store):
            if v < self._next_version:
                continue
            rows = []
            for action in _version_actions(self.store, v):
                if "add" in action:
                    part = action["add"]["path"]
                    with self.store.open_read(self.store.join(part)) as f:
                        part_rows = _rows_from_parquet(
                            f, self.column_names, self.schema, self._counter
                        )
                    self._live[part] = part_rows
                    # dataChange=false (compaction): rows merely moved
                    # files — track them, emit nothing
                    if action["add"].get("dataChange", True):
                        rows.extend(part_rows)
                elif "remove" in action:
                    part = action["remove"]["path"]
                    dropped = self._live.pop(part, [])
                    if action["remove"].get("dataChange", True):
                        for k, d, vals in dropped:
                            rows.append((k, -d, vals))
            self._next_version = v + 1
            if rows:
                self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    uri: str,
    *,
    schema: Any,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    storage_options: dict | None = None,
    **kwargs: Any,
) -> Table:
    column_names = list(schema.column_names())
    store = _Store(uri, storage_options)
    if mode == "static":
        source: Any = _DeltaStaticSource(store, column_names, schema)
    else:
        source = _DeltaStreamingSource(store, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())


def _schema_desc(table: Table) -> list[dict]:
    return [
        {"name": n, "type": str(d)}
        for n, d in table._schema.dtypes().items()
    ]


class _DeltaWriter:
    def __init__(
        self,
        store: _Store,
        column_names,
        schema_desc: list[dict] | None = None,
        *,
        mode: str = "append",
        schema_evolution: str = "strict",
        compact_every: int | None = None,
    ):
        self.store = store
        self.column_names = list(column_names)
        self.schema_desc = schema_desc or [
            {"name": n, "type": "any"} for n in column_names
        ]
        self.compact_every = compact_every
        self._commits_since_compact = 0
        if not store._local:
            import warnings

            warnings.warn(
                f"deltalake writer over {store.protocol}://: fsspec has no "
                "atomic create-if-absent, so the optimistic commit degrades "
                "to exists-check-then-write (TOCTOU). Concurrent writers on "
                "this store need external coordination (e.g. a DynamoDB-style "
                "lock) to avoid last-writer-wins on the Delta log.",
                stacklevel=3,
            )
        store.makedirs(store.join(_LOG_DIR))
        versions = _list_versions(store)
        self.version = (versions[-1] + 1) if versions else 0
        if self.version == 0:
            self._commit(
                [
                    {
                        "protocol": {
                            "minReaderVersion": 1,
                            "minWriterVersion": 2,
                        }
                    },
                    self._metadata_action(),
                ]
            )
        else:
            self._check_schema(schema_evolution)
        # overwrite: removes are DEFERRED into the same commit as the
        # first data batch — delta overwrite semantics are one atomic
        # remove+add commit, and a pipeline that aborts before producing
        # data must not have emptied the table
        self._pending_removes: list[dict] = []
        if self.version > 0 and mode == "overwrite":
            files, _m = _replay_log(store)
            self._pending_removes = [
                {"remove": {"path": p, "dataChange": True}} for p in files
            ]

    def _metadata_action(self) -> dict:
        return {
            "metaData": {
                "id": str(uuid.uuid4()),
                "format": {"provider": "parquet"},
                "schemaString": _json.dumps(
                    {
                        "columns": self.column_names,
                        "fields": self.schema_desc,
                    }
                ),
            }
        }

    def _check_schema(self, evolution: str) -> None:
        """Evolution guard (reference: data_lake writer schema checks):
        identical schemas append; NEW columns are allowed only with
        schema_evolution='allow_add' (commits a fresh metaData action);
        dropped or type-changed columns are refused."""
        _files, meta = _replay_log(self.store)
        if not meta:
            return
        existing = {
            f["name"]: f.get("type", "any")
            for f in meta.get("fields", [])
        } or {c: "any" for c in meta.get("columns", [])}
        mine = {f["name"]: f["type"] for f in self.schema_desc}
        dropped = set(existing) - set(mine)
        if dropped:
            raise ValueError(
                f"deltalake: writer schema drops existing column(s) "
                f"{sorted(dropped)}; refusing to append"
            )
        changed = {
            n
            for n in existing
            if existing[n] not in ("any", mine[n]) and mine[n] != "any"
        }
        if changed:
            raise ValueError(
                f"deltalake: writer changes type of column(s) "
                f"{sorted(changed)}; refusing to append"
            )
        added = set(mine) - set(existing)
        if added:
            if evolution != "allow_add":
                raise ValueError(
                    f"deltalake: writer adds new column(s) {sorted(added)}; "
                    "pass schema_evolution='allow_add' to evolve the table"
                )
            self._commit([self._metadata_action()])

    def _commit(self, actions: list[dict]) -> None:
        """Optimistic transactional commit: the version file is created
        exclusively; a collision (concurrent writer won the version) bumps
        the version and retries. Atomic on local filesystems; on plain
        object stores the exists-check is best-effort (see module
        docstring)."""
        data = (
            "\n".join(_json.dumps(a) for a in actions) + "\n"
        ).encode()
        while True:
            path = _log_path(self.store, self.version)
            if self.store.create_exclusive(path, data):
                self.version += 1
                return
            self.version += 1  # lost the race: retry at the next version

    def write_batch(self, t: int, batch: DiffBatch) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq
        import io

        cols: dict[str, list] = {n: [] for n in self.column_names}
        times: list[int] = []
        diffs: list[int] = []
        for _k, d, vals in batch.iter_rows():
            for n, v in zip(self.column_names, vals):
                cols[n].append(jsonable(v))
            times.append(t)
            diffs.append(d)
        cols["time"] = times
        cols["diff"] = diffs
        part = f"part-{self.version:05d}-{uuid.uuid4().hex}.parquet"
        buf = io.BytesIO()
        pq.write_table(pa.table(cols), buf)
        fpath = self.store.join(part)
        self.store.write_bytes(fpath, buf.getvalue())
        actions = self._pending_removes + [
            {
                "add": {
                    "path": part,
                    "size": self.store.size(fpath),
                    "dataChange": True,
                }
            }
        ]
        self._pending_removes = []
        self._commit(actions)
        self._commits_since_compact += 1
        if (
            self.compact_every
            and self._commits_since_compact >= self.compact_every
        ):
            self.compact()

    def compact(self) -> None:
        """Merge every active part into one parquet (remove+add in a
        single commit — the reference's maintenance/optimize pass). Old
        parts stay on disk for readers of older versions (vacuum is a
        separate concern)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        import io

        self._commits_since_compact = 0
        files, _meta = _replay_log(self.store)
        if len(files) <= 1:
            return
        tables = []
        for part in files:
            with self.store.open_read(self.store.join(part)) as f:
                tables.append(pq.read_table(f))
        merged = pa.concat_tables(tables, promote_options="default")
        part = f"part-{self.version:05d}-{uuid.uuid4().hex}.parquet"
        buf = io.BytesIO()
        pq.write_table(merged, buf)
        self.store.write_bytes(self.store.join(part), buf.getvalue())
        actions = [
            {"remove": {"path": p, "dataChange": False}} for p in files
        ]
        actions.append(
            {
                "add": {
                    "path": part,
                    "size": self.store.size(self.store.join(part)),
                    "dataChange": False,
                }
            }
        )
        self._commit(actions)


def write(
    table: Table,
    uri: str,
    *,
    mode: str = "append",
    schema_evolution: str = "strict",
    compact_every: int | None = None,
    storage_options: dict | None = None,
    **kwargs: Any,
) -> None:
    store = _Store(uri, storage_options)
    writer = _DeltaWriter(
        store,
        table.column_names(),
        _schema_desc(table),
        mode=mode,
        schema_evolution=schema_evolution,
        compact_every=compact_every,
    )
    add_writer(table, writer.write_batch)
