"""pw.io.deltalake — Delta Lake source/sink on pyarrow.

TPU-native counterpart of the reference's DeltaLake connector
(reference: src/connectors/data_lake/{mod,delta,writer}.rs — arrow-based
batch/streaming readers and transactional writers). The image has pyarrow
but no `deltalake` package, so this implements the core of the Delta
protocol directly: parquet part files plus an ordered `_delta_log/` of
JSON commits with `add` actions. Writes are transactional (parquet written
first, then the commit file appears atomically via rename); the streaming
reader tails the log for new versions. Output rows carry `time`/`diff`
columns like the reference writer.
"""

from __future__ import annotations

import json as _json
import os
import threading
import uuid
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable

_LOG_DIR = "_delta_log"


def _log_path(root: str, version: int) -> str:
    return os.path.join(root, _LOG_DIR, f"{version:020d}.json")


def _list_versions(root: str) -> list[int]:
    log_dir = os.path.join(root, _LOG_DIR)
    if not os.path.isdir(log_dir):
        return []
    out = []
    for f in os.listdir(log_dir):
        if f.endswith(".json"):
            try:
                out.append(int(f[:-5]))
            except ValueError:
                pass
    return sorted(out)


def _read_version_files(root: str, version: int) -> list[str]:
    """Parquet files added by one commit."""
    files = []
    with open(_log_path(root, version)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            action = _json.loads(line)
            if "add" in action:
                files.append(os.path.join(root, action["add"]["path"]))
    return files


def _rows_from_parquet(
    path: str, column_names, schema, counter
) -> list[tuple[int, int, tuple]]:
    import pyarrow.parquet as pq

    tbl = pq.read_table(path)
    data = tbl.to_pylist()
    dtypes = schema.dtypes() if schema else {}
    pk = schema.primary_key_columns() if schema else None
    rows = []
    for obj in data:
        vals = []
        for c in column_names:
            v = obj.get(c)
            d = dtypes.get(c, dt.ANY).strip_optional()
            if d == dt.FLOAT and isinstance(v, int):
                v = float(v)
            vals.append(v)
        vals = tuple(vals)
        diff = int(obj.get("diff", 1))
        if pk:
            key = int(ref_scalar(*[vals[column_names.index(c)] for c in pk]))
        else:
            # key by value content so a -1 row cancels its earlier +1 even
            # without a declared primary key (sequential keys would orphan
            # retractions); identical duplicates coexist via multiplicity
            key = int(ref_scalar(*vals))
        rows.append((key, diff, vals))
    return rows


class _DeltaStaticSource(StaticSource):
    def __init__(self, root, column_names, schema):
        super().__init__(column_names)
        self.root = root
        self.schema = schema

    def events(self):
        import itertools

        counter = itertools.count()
        rows = []
        for v in _list_versions(self.root):
            for f in _read_version_files(self.root, v):
                rows.extend(
                    _rows_from_parquet(f, self.column_names, self.schema, counter)
                )
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _DeltaStreamingSource(StreamingSource):
    def __init__(self, root, column_names, schema, refresh_s=0.2):
        super().__init__(column_names)
        self.root = root
        self.schema = schema
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread = None
        self._next_version = 0
        import itertools

        self._counter = itertools.count()

    def offset_state(self) -> dict:
        return {"next_version": self._next_version}

    def seek(self, state: dict) -> None:
        self._next_version = int(state.get("next_version", 0))

    def _scan(self):
        for v in _list_versions(self.root):
            if v < self._next_version:
                continue
            rows = []
            for f in _read_version_files(self.root, v):
                rows.extend(
                    _rows_from_parquet(
                        f, self.column_names, self.schema, self._counter
                    )
                )
            self._next_version = v + 1
            if rows:
                self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    uri: str,
    *,
    schema: Any,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    column_names = list(schema.column_names())
    if mode == "static":
        source: Any = _DeltaStaticSource(uri, column_names, schema)
    else:
        source = _DeltaStreamingSource(uri, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())


class _DeltaWriter:
    def __init__(self, root: str, column_names):
        self.root = root
        self.column_names = list(column_names)
        os.makedirs(os.path.join(root, _LOG_DIR), exist_ok=True)
        versions = _list_versions(root)
        self.version = (versions[-1] + 1) if versions else 0
        if self.version == 0:
            self._commit(
                [
                    {
                        "protocol": {
                            "minReaderVersion": 1,
                            "minWriterVersion": 2,
                        }
                    },
                    {
                        "metaData": {
                            "id": str(uuid.uuid4()),
                            "format": {"provider": "parquet"},
                            "schemaString": _json.dumps(
                                {"columns": self.column_names}
                            ),
                        }
                    },
                ]
            )

    def _commit(self, actions: list[dict]) -> None:
        # parquet first, commit file last + atomic rename = transactional
        path = _log_path(self.root, self.version)
        tmp = path + f".tmp-{uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            for a in actions:
                f.write(_json.dumps(a) + "\n")
        os.replace(tmp, path)
        self.version += 1

    def write_batch(self, t: int, batch: DiffBatch) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: dict[str, list] = {n: [] for n in self.column_names}
        times: list[int] = []
        diffs: list[int] = []
        for _k, d, vals in batch.iter_rows():
            for n, v in zip(self.column_names, vals):
                cols[n].append(jsonable(v))
            times.append(t)
            diffs.append(d)
        cols["time"] = times
        cols["diff"] = diffs
        part = f"part-{self.version:05d}-{uuid.uuid4().hex}.parquet"
        fpath = os.path.join(self.root, part)
        pq.write_table(pa.table(cols), fpath)
        self._commit(
            [
                {
                    "add": {
                        "path": part,
                        "size": os.path.getsize(fpath),
                        "dataChange": True,
                    }
                }
            ]
        )


def write(table: Table, uri: str, **kwargs: Any) -> None:
    writer = _DeltaWriter(uri, table.column_names())
    add_writer(table, writer.write_batch)
