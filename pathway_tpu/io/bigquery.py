"""pw.io.bigquery — BigQuery sink via the google-cloud-bigquery client
(reference: python/pathway/io/bigquery — insert_rows_json streaming
writes). Credentials resolve through the standard ADC chain at run time."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, require, row_dicts


def write(
    table,
    dataset_name: str,
    table_name: str,
    *,
    service_user_credentials_file: str | None = None,
    **kwargs: Any,
) -> None:
    bigquery = require("google.cloud.bigquery", "bigquery")
    if service_user_credentials_file:
        from google.oauth2.service_account import Credentials  # type: ignore

        creds = Credentials.from_service_account_file(
            service_user_credentials_file
        )
        client = bigquery.Client(credentials=creds)
    else:
        client = bigquery.Client()
    column_names = table.column_names()
    target = f"{dataset_name}.{table_name}"

    def on_batch(t: int, batch: DiffBatch) -> None:
        rows = []
        for _k, d, doc in row_dicts(batch, column_names, t):
            doc["time"] = t
            doc["diff"] = d
            rows.append(doc)
        if rows:
            errors = client.insert_rows_json(target, rows)
            if errors:
                raise RuntimeError(f"bigquery insert errors: {errors}")

    add_writer(table, on_batch, client.close)
