"""pw.io.jsonlines (reference: python/pathway/io/jsonlines)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs as _fs


def read(
    path: str,
    *,
    schema: Any = None,
    mode: str = "streaming",
    json_field_paths: dict | None = None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
):
    return _fs.read(
        path,
        format="json",
        schema=schema,
        mode=mode,
        json_field_paths=json_field_paths,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name,
        **kwargs,
    )


def write(table, filename: str, *, name: str | None = None, **kwargs) -> None:
    _fs.write(table, filename, format="json", **kwargs)
