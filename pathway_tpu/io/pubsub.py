"""pw.io.pubsub — Google Cloud Pub/Sub sink
(reference: python/pathway/io/pubsub). Requires google-cloud-pubsub at
call time."""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, require, row_dicts


def write(table, publisher: Any = None, project_id: str | None = None,
          topic_id: str | None = None, **kwargs: Any) -> None:
    if publisher is None:
        pubsub = require("google.cloud.pubsub_v1", "pubsub")
        publisher = pubsub.PublisherClient()
    topic_path = publisher.topic_path(project_id, topic_id)
    column_names = table.column_names()

    def on_batch(t: int, batch: DiffBatch) -> None:
        futures = []
        for k, d, doc in row_dicts(batch, column_names, t):
            futures.append(
                publisher.publish(
                    topic_path,
                    _json.dumps(doc).encode(),
                    pathway_time=str(t),
                    pathway_diff=str(d),
                    pathway_key=f"{k:016x}",
                )
            )
        for f in futures:
            f.result(timeout=60)

    add_writer(table, on_batch)
