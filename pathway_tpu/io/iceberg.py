"""pw.io.iceberg — Iceberg-style table source/sink
(reference: src/connectors/data_lake/iceberg.rs). The image has no
`pyiceberg`; this speaks a compatible subset of the spec on pyarrow:
parquet data files tracked by versioned JSON snapshots under `metadata/`
with a `version-hint.text` pointer (the layout pyiceberg's filesystem
catalog reads). Full-catalog deployments should install `pyiceberg`."""

from __future__ import annotations

import json as _json
import os
import threading
import uuid
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable
from pathway_tpu.io.deltalake import _rows_from_parquet


def _meta_dir(root: str) -> str:
    return os.path.join(root, "metadata")


def _current_version(root: str) -> int:
    hint = os.path.join(_meta_dir(root), "version-hint.text")
    try:
        with open(hint) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return -1


def _snapshot_files(root: str, version: int) -> list[str]:
    path = os.path.join(_meta_dir(root), f"v{version}.metadata.json")
    try:
        with open(path) as f:
            meta = _json.loads(f.read())
    except OSError:
        return []
    return [os.path.join(root, "data", p) for p in meta.get("files", [])]


class _IcebergStaticSource(StaticSource):
    def __init__(self, root, column_names, schema):
        super().__init__(column_names)
        self.root = root
        self.schema = schema

    def events(self):
        import itertools

        counter = itertools.count()
        v = _current_version(self.root)
        rows = []
        if v >= 0:
            for f in _snapshot_files(self.root, v):
                rows.extend(
                    _rows_from_parquet(f, self.column_names, self.schema, counter)
                )
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _IcebergStreamingSource(StreamingSource):
    """Tail the version hint; emit only files added since the last seen
    snapshot."""

    def __init__(self, root, column_names, schema, refresh_s=0.2):
        super().__init__(column_names)
        self.root = root
        self.schema = schema
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread = None
        self._seen_files: set[str] = set()
        self._version = -1
        import itertools

        self._counter = itertools.count()

    def offset_state(self) -> dict:
        return {"version": self._version, "files": sorted(self._seen_files)}

    def seek(self, state: dict) -> None:
        self._version = int(state.get("version", -1))
        self._seen_files = set(state.get("files", []))

    def _scan(self):
        v = _current_version(self.root)
        if v < 0 or v == self._version:
            return
        rows = []
        for f in _snapshot_files(self.root, v):
            if f in self._seen_files:
                continue
            rows.extend(
                _rows_from_parquet(f, self.column_names, self.schema, self._counter)
            )
            self._seen_files.add(f)
        self._version = v
        if rows:
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    catalog_uri: str,
    *,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    schema: Any,
    mode: str = "streaming",
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    root = catalog_uri
    if namespace or table_name:
        parts = list(namespace or []) + ([table_name] if table_name else [])
        root = os.path.join(catalog_uri, *parts)
    column_names = list(schema.column_names())
    if mode == "static":
        source: Any = _IcebergStaticSource(root, column_names, schema)
    else:
        source = _IcebergStreamingSource(root, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())


class _IcebergWriter:
    def __init__(self, root, column_names):
        self.root = root
        self.column_names = list(column_names)
        os.makedirs(_meta_dir(root), exist_ok=True)
        os.makedirs(os.path.join(root, "data"), exist_ok=True)
        self.version = _current_version(root)
        self.files: list[str] = (
            [
                os.path.relpath(f, os.path.join(root, "data"))
                for f in _snapshot_files(root, self.version)
            ]
            if self.version >= 0
            else []
        )

    def write_batch(self, t: int, batch: DiffBatch) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: dict[str, list] = {n: [] for n in self.column_names}
        times, diffs = [], []
        for _k, d, vals in batch.iter_rows():
            for n, v in zip(self.column_names, vals):
                cols[n].append(jsonable(v))
            times.append(t)
            diffs.append(d)
        cols["time"] = times
        cols["diff"] = diffs
        fname = f"{uuid.uuid4().hex}.parquet"
        pq.write_table(pa.table(cols), os.path.join(self.root, "data", fname))
        self.files.append(fname)
        self.version += 1
        meta_path = os.path.join(
            _meta_dir(self.root), f"v{self.version}.metadata.json"
        )
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(_json.dumps({"files": self.files}))
        os.replace(tmp, meta_path)
        hint = os.path.join(_meta_dir(self.root), "version-hint.text")
        tmp = hint + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self.version))
        os.replace(tmp, hint)


def write(
    table: Table,
    catalog_uri: str,
    *,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    **kwargs: Any,
) -> None:
    root = catalog_uri
    if namespace or table_name:
        parts = list(namespace or []) + ([table_name] if table_name else [])
        root = os.path.join(catalog_uri, *parts)
    writer = _IcebergWriter(root, table.column_names())
    add_writer(table, writer.write_batch)
