"""pw.io.iceberg — Iceberg-style table source/sink
(reference: src/connectors/data_lake/iceberg.rs). The image has no
`pyiceberg`; this speaks a compatible subset of the spec on pyarrow:
parquet data files tracked by versioned JSON snapshots under `metadata/`
with a `version-hint.text` pointer (the layout pyiceberg's filesystem
catalog reads). Snapshots carry the table schema and a snapshot-history
list; appending writers are schema-guarded (new columns require
``schema_evolution="allow_add"``, drops/type changes are refused), the
``mode="overwrite"`` writer starts a snapshot containing only its own
files, and the streaming reader RETRACTS rows of files that leave the
snapshot, so overwrites flow as incremental updates. Full-catalog
deployments should install `pyiceberg`."""

from __future__ import annotations

import json as _json
import os
import threading
import uuid
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable
from pathway_tpu.io.deltalake import _rows_from_parquet, create_exclusive_local


def _meta_dir(root: str) -> str:
    return os.path.join(root, "metadata")


def _current_version(root: str) -> int:
    """Latest committed snapshot version. version-hint.text is advisory
    (its write is last-writer-wins, so a slow writer can regress it);
    the truth is the densely-numbered vN.metadata.json files — probe
    upward from the hint until the next version is absent, exactly how
    pyiceberg's filesystem catalog recovers from a stale hint."""
    hint = os.path.join(_meta_dir(root), "version-hint.text")
    try:
        with open(hint) as f:
            v = int(f.read().strip())
    except (OSError, ValueError):
        v = -1
    while os.path.exists(
        os.path.join(_meta_dir(root), f"v{v + 1}.metadata.json")
    ):
        v += 1
    return v


def _snapshot_meta(root: str, version: int) -> dict:
    path = os.path.join(_meta_dir(root), f"v{version}.metadata.json")
    try:
        with open(path) as f:
            return _json.loads(f.read())
    except OSError:
        return {}


def _snapshot_files(root: str, version: int) -> list[str]:
    return [
        os.path.join(root, "data", p)
        for p in _snapshot_meta(root, version).get("files", [])
    ]


class _IcebergStaticSource(StaticSource):
    def __init__(self, root, column_names, schema):
        super().__init__(column_names)
        self.root = root
        self.schema = schema

    def events(self):
        import itertools

        counter = itertools.count()
        v = _current_version(self.root)
        rows = []
        if v >= 0:
            for f in _snapshot_files(self.root, v):
                rows.extend(
                    _rows_from_parquet(f, self.column_names, self.schema, counter)
                )
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _IcebergStreamingSource(StreamingSource):
    """Tail the version hint; emit only files added since the last seen
    snapshot."""

    def __init__(self, root, column_names, schema, refresh_s=0.2):
        super().__init__(column_names)
        self.root = root
        self.schema = schema
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread = None
        self._seen_files: set[str] = set()
        # file -> contributed rows, for retraction when a snapshot drops it
        self._live: dict[str, list] = {}
        self._version = -1
        import itertools

        self._counter = itertools.count()

    def offset_state(self) -> dict:
        return {"version": self._version, "files": sorted(self._seen_files)}

    def seek(self, state: dict) -> None:
        self._version = int(state.get("version", -1))
        self._seen_files = set(state.get("files", []))
        # rebuild the live map WITHOUT emitting (rows were delivered
        # before the restart; the persistence input log replays them)
        for f in self._seen_files:
            try:
                self._live[f] = _rows_from_parquet(
                    f, self.column_names, self.schema, self._counter
                )
            except OSError:
                pass

    def _scan(self):
        v = _current_version(self.root)
        if v < 0 or v == self._version:
            return
        current = set(_snapshot_files(self.root, v))
        rows = []
        # files dropped by the new snapshot (overwrite): retract their rows
        for f in sorted(self._seen_files - current):
            for k, d, vals in self._live.pop(f, []):
                rows.append((k, -d, vals))
            self._seen_files.discard(f)
        for f in sorted(current - self._seen_files):
            part_rows = _rows_from_parquet(
                f, self.column_names, self.schema, self._counter
            )
            self._live[f] = part_rows
            rows.extend(part_rows)
            self._seen_files.add(f)
        self._version = v
        if rows:
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    catalog_uri: str,
    *,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    schema: Any,
    mode: str = "streaming",
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    root = catalog_uri
    if namespace or table_name:
        parts = list(namespace or []) + ([table_name] if table_name else [])
        root = os.path.join(catalog_uri, *parts)
    column_names = list(schema.column_names())
    if mode == "static":
        source: Any = _IcebergStaticSource(root, column_names, schema)
    else:
        source = _IcebergStreamingSource(root, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())


class _IcebergWriter:
    def __init__(
        self,
        root,
        column_names,
        schema_desc: list[dict] | None = None,
        *,
        mode: str = "append",
        schema_evolution: str = "strict",
    ):
        self.root = root
        self.column_names = list(column_names)
        self.mode = mode
        self.schema_desc = schema_desc or [
            {"name": n, "type": "any"} for n in column_names
        ]
        os.makedirs(_meta_dir(root), exist_ok=True)
        os.makedirs(os.path.join(root, "data"), exist_ok=True)
        self.version = _current_version(root)
        if self.version >= 0:
            self._check_schema(schema_evolution)
        # overwrite: the fresh (files-of-this-writer-only) snapshot is
        # committed WITH the first data batch, not at construction — an
        # aborted pipeline must not have emptied the table
        # files written since the last successful commit — the rebase unit
        # on commit races (files already in one of our committed snapshots
        # must NOT be re-added: a concurrent overwrite may have dropped them)
        self.pending_files: list[str] = []
        self.files: list[str] = (
            [
                os.path.relpath(f, os.path.join(root, "data"))
                for f in _snapshot_files(root, self.version)
            ]
            if self.version >= 0 and mode != "overwrite"
            else []
        )

    def _check_schema(self, evolution: str) -> None:
        """Evolution guard (mirrors pw.io.deltalake): identical schemas
        append; new columns need schema_evolution='allow_add'; dropped or
        type-changed columns are refused."""
        meta = _snapshot_meta(self.root, self.version)
        fields = meta.get("schema", {}).get("fields")
        if not fields:
            return
        existing = {f["name"]: f.get("type", "any") for f in fields}
        mine = {f["name"]: f["type"] for f in self.schema_desc}
        dropped = set(existing) - set(mine)
        if dropped:
            raise ValueError(
                f"iceberg: writer schema drops existing column(s) "
                f"{sorted(dropped)}; refusing to append"
            )
        changed = {
            n
            for n in existing
            if existing[n] not in ("any", mine[n]) and mine[n] != "any"
        }
        if changed:
            raise ValueError(
                f"iceberg: writer changes type of column(s) "
                f"{sorted(changed)}; refusing to append"
            )
        added = set(mine) - set(existing)
        if added and evolution != "allow_add":
            raise ValueError(
                f"iceberg: writer adds new column(s) {sorted(added)}; "
                "pass schema_evolution='allow_add' to evolve the table"
            )

    def _commit_snapshot(self) -> None:
        import time as _time

        while True:
            prev = _snapshot_meta(self.root, self.version)
            snapshots = list(prev.get("snapshots", []))
            next_version = self.version + 1
            snapshot = {
                "snapshot-id": next_version,
                "timestamp-ms": int(_time.time() * 1000),
                "files": list(self.files),
            }
            meta = {
                "files": list(self.files),
                "schema": {"fields": self.schema_desc},
                "snapshots": (snapshots + [snapshot])[-64:],  # bounded history
            }
            meta_path = os.path.join(
                _meta_dir(self.root), f"v{next_version}.metadata.json"
            )
            if create_exclusive_local(meta_path, _json.dumps(meta).encode()):
                self.version = next_version
                self.pending_files = []
                break
            # a concurrent writer won version next_version: rebase this
            # writer's OWN files onto the winner's list (append mode —
            # unioning our stale base snapshot would resurrect files a
            # concurrent overwrite just dropped) and retry one version up.
            # An overwrite snapshot stays authoritative: only its own files.
            if self.mode != "overwrite":
                theirs = _snapshot_meta(self.root, next_version).get("files", [])
                self.files = list(
                    dict.fromkeys([*theirs, *self.pending_files])
                )
            self.version = next_version
        # the hint is advisory (readers probe upward from it, see
        # _current_version), so a racing last-writer-wins replace here can
        # at worst cost readers a few extra stat calls, never data
        hint = os.path.join(_meta_dir(self.root), "version-hint.text")
        tmp = hint + f".tmp-{uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            f.write(str(self.version))
        os.replace(tmp, hint)

    def write_batch(self, t: int, batch: DiffBatch) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: dict[str, list] = {n: [] for n in self.column_names}
        times, diffs = [], []
        for _k, d, vals in batch.iter_rows():
            for n, v in zip(self.column_names, vals):
                cols[n].append(jsonable(v))
            times.append(t)
            diffs.append(d)
        cols["time"] = times
        cols["diff"] = diffs
        fname = f"{uuid.uuid4().hex}.parquet"
        pq.write_table(pa.table(cols), os.path.join(self.root, "data", fname))
        self.files.append(fname)
        self.pending_files.append(fname)
        self._commit_snapshot()


def write(
    table: Table,
    catalog_uri: str,
    *,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    mode: str = "append",
    schema_evolution: str = "strict",
    **kwargs: Any,
) -> None:
    from pathway_tpu.io.deltalake import _schema_desc

    root = catalog_uri
    if namespace or table_name:
        parts = list(namespace or []) + ([table_name] if table_name else [])
        root = os.path.join(catalog_uri, *parts)
    writer = _IcebergWriter(
        root,
        table.column_names(),
        _schema_desc(table),
        mode=mode,
        schema_evolution=schema_evolution,
    )
    add_writer(table, writer.write_batch)
