"""pw.io.kafka — Kafka source/sink.

TPU-native counterpart of the reference's KafkaReader/KafkaWriter
(reference: src/connectors/data_storage.rs:697,1368 over rdkafka; Python
façade python/pathway/io/kafka, 676 LoC). Uses `confluent_kafka` when
present (not baked into this image — the connector raises a clear error at
call time, and the parsing/formatting layer is shared with the fs
connector so message semantics match: raw / json / dsv formats, optional
key from primary-key columns).
"""

from __future__ import annotations

import json as _json
import threading
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar, sequential_key
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable, require


def _parse_message(raw: bytes, format: str, column_names, schema, counter):
    if format in ("raw", "plaintext"):
        data = raw if format == "raw" else raw.decode("utf-8", errors="replace")
        return [(int(sequential_key(next(counter))), (data,))]
    if format == "json":
        obj = _json.loads(raw)
        dtypes = schema.dtypes() if schema else {}
        vals = []
        for c in column_names:
            v = obj.get(c)
            d = dtypes.get(c, dt.ANY).strip_optional()
            if d == dt.JSON and not isinstance(v, Json):
                v = Json(v)
            elif d == dt.FLOAT and isinstance(v, int):
                v = float(v)
            vals.append(v)
        vals = tuple(vals)
        pk = schema.primary_key_columns() if schema else None
        if pk:
            key = int(ref_scalar(*[vals[column_names.index(c)] for c in pk]))
        else:
            key = int(sequential_key(next(counter)))
        return [(key, vals)]
    raise ValueError(f"unsupported kafka format {format!r}")


class _KafkaSource(StreamingSource):
    def __init__(self, settings, topic, format, column_names, schema):
        super().__init__(column_names)
        self._ck = require(
            "confluent_kafka",
            "kafka",
            hint="Use pw.io.fs / pw.io.python connectors locally.",
        )
        self.settings = settings
        self.topic = topic
        self.format = format
        self.schema = schema
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._offsets: dict[int, int] = {}  # partition -> next offset

    def offset_state(self) -> dict:
        return {"offsets": dict(self._offsets)}

    def seek(self, state: dict) -> None:
        self._offsets = dict(state.get("offsets", {}))

    def _loop(self):
        import itertools

        counter = itertools.count()
        consumer = self._ck.Consumer(self.settings)

        def on_assign(cons, partitions):
            # seek must wait for assignment (rdkafka raises otherwise)
            if self._offsets:
                for p in partitions:
                    if p.partition in self._offsets:
                        p.offset = self._offsets[p.partition]
                cons.assign(partitions)

        consumer.subscribe([self.topic], on_assign=on_assign)
        while not self._stop.is_set():
            msg = consumer.poll(0.2)
            if msg is None or msg.error():
                continue
            rows = [
                (key, 1, vals)
                for key, vals in _parse_message(
                    msg.value(), self.format, self.column_names, self.schema,
                    counter,
                )
            ]
            self._offsets[msg.partition()] = msg.offset() + 1
            self.session.insert_batch(rows, self.offset_state())
        consumer.close()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


def read(
    rdkafka_settings: dict,
    topic: str | None = None,
    *,
    schema: Any = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    topic_names: list[str] | None = None,
    **kwargs: Any,
) -> Table:
    if topic is None and topic_names:
        topic = topic_names[0]
    if format in ("raw", "plaintext"):
        column_names = ["data"]
        dtypes = {"data": dt.BYTES if format == "raw" else dt.STR}
    else:
        assert schema is not None, "schema required for json format"
        column_names = list(schema.column_names())
        dtypes = dict(schema.dtypes())
    source = _KafkaSource(rdkafka_settings, topic, format, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dtypes, Universe())


def write(
    table: Table,
    rdkafka_settings: dict,
    topic_name: str,
    *,
    format: str = "json",
    **kwargs: Any,
) -> None:
    ck = require("confluent_kafka", "kafka")
    producer = ck.Producer(rdkafka_settings)
    column_names = table.column_names()

    def on_batch(t: int, batch: DiffBatch) -> None:
        for k, d, vals in batch.iter_rows():
            payload = {
                n: jsonable(v) for n, v in zip(column_names, vals)
            }
            payload["time"] = t
            payload["diff"] = d
            producer.produce(
                topic_name,
                key=f"{k:016x}".encode(),
                value=_json.dumps(payload).encode(),
            )
        producer.flush()

    add_writer(table, on_batch)
