"""pw.io.postgres — PostgreSQL sink.

TPU-native counterpart of the reference's PsqlWriter + formatters
(reference: src/connectors/data_storage.rs:1059 PsqlWriter;
data_format.rs:1712 PsqlUpdatesFormatter — INSERT with time/diff columns;
:1771 PsqlSnapshotFormatter — exactly-once upserts on primary key).
Requires `psycopg2` (or psycopg) at call time.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, jsonable


def _connect(postgres_settings: dict):
    try:
        import psycopg2 as pg  # type: ignore[import-not-found]
    except ImportError:
        from pathway_tpu.io._utils import require

        pg = require("psycopg", "postgres")
    return pg.connect(**postgres_settings)


def write(
    table,
    postgres_settings: dict,
    table_name: str,
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    **kwargs: Any,
) -> None:
    """Stream-of-updates mode: append rows with time/diff columns
    (reference: PsqlUpdatesFormatter)."""
    column_names = table.column_names()
    state: dict[str, Any] = {"conn": None}

    def conn():
        if state["conn"] is None:
            state["conn"] = _connect(postgres_settings)
            if init_mode in ("create", "create_if_not_exists", "replace"):
                with state["conn"].cursor() as cur:
                    if init_mode == "replace":
                        cur.execute(f'DROP TABLE IF EXISTS "{table_name}"')
                    cols = ", ".join(f'"{c}" TEXT' for c in column_names)
                    cur.execute(
                        f'CREATE TABLE IF NOT EXISTS "{table_name}" '
                        f"({cols}, time BIGINT, diff BIGINT)"
                    )
                state["conn"].commit()
        return state["conn"]

    def on_batch(t: int, batch: DiffBatch) -> None:
        c = conn()
        cols = ", ".join(f'"{n}"' for n in column_names)
        ph = ", ".join(["%s"] * (len(column_names) + 2))
        with c.cursor() as cur:
            for _k, d, vals in batch.iter_rows():
                cur.execute(
                    f'INSERT INTO "{table_name}" ({cols}, time, diff) '  # noqa: S608
                    f"VALUES ({ph})",
                    tuple(jsonable(v) for v in vals) + (t, d),
                )
        c.commit()

    def on_end():
        if state["conn"] is not None:
            state["conn"].close()

    add_writer(table, on_batch, on_end)


def write_snapshot(
    table,
    postgres_settings: dict,
    table_name: str,
    primary_key: list[str],
    *,
    max_batch_size: int | None = None,
    **kwargs: Any,
) -> None:
    """Snapshot mode: upsert on primary key, delete on retraction
    (reference: PsqlSnapshotFormatter, data_format.rs:1771)."""
    column_names = table.column_names()
    state: dict[str, Any] = {"conn": None}

    def conn():
        if state["conn"] is None:
            state["conn"] = _connect(postgres_settings)
        return state["conn"]

    def on_batch(t: int, batch: DiffBatch) -> None:
        c = conn()
        cols = ", ".join(f'"{n}"' for n in column_names)
        ph = ", ".join(["%s"] * len(column_names))
        pk_cols = ", ".join(f'"{c_}"' for c_ in primary_key)
        updates = ", ".join(
            f'"{n}" = EXCLUDED."{n}"'
            for n in column_names
            if n not in primary_key
        )
        with c.cursor() as cur:
            for _k, d, vals in batch.iter_rows():
                row = {n: jsonable(v) for n, v in zip(column_names, vals)}
                if d > 0:
                    sql = (
                        f'INSERT INTO "{table_name}" ({cols}) VALUES ({ph}) '  # noqa: S608
                        f"ON CONFLICT ({pk_cols}) DO UPDATE SET {updates}"
                        if updates
                        else f'INSERT INTO "{table_name}" ({cols}) VALUES ({ph}) '  # noqa: S608
                        f"ON CONFLICT ({pk_cols}) DO NOTHING"
                    )
                    cur.execute(sql, tuple(row[n] for n in column_names))
                else:
                    cond = " AND ".join(f'"{c_}" = %s' for c_ in primary_key)
                    cur.execute(
                        f'DELETE FROM "{table_name}" WHERE {cond}',  # noqa: S608
                        tuple(row[c_] for c_ in primary_key),
                    )
        c.commit()

    def on_end():
        if state["conn"] is not None:
            state["conn"].close()

    add_writer(table, on_batch, on_end)
