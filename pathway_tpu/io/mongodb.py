"""pw.io.mongodb — MongoDB sink (reference: MongoWriter,
src/connectors/data_storage.rs:1732 + BsonFormatter data_format.rs:2068).
Requires `pymongo` at call time."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, require, row_dicts


def write(
    table,
    connection_string: str,
    database: str,
    collection: str,
    *,
    max_batch_size: int | None = None,
    **kwargs: Any,
) -> None:
    pymongo = require("pymongo", "mongodb")
    client = pymongo.MongoClient(connection_string)
    coll = client[database][collection]
    column_names = table.column_names()

    def on_batch(t: int, batch: DiffBatch) -> None:
        # append-only event stream: every change (including retractions) is
        # its own document with time/diff and a server-generated _id
        # (reference: BsonFormatter emits the diff stream the same way)
        ops = []
        for k, d, doc in row_dicts(batch, column_names, t):
            doc["key"] = f"{k:016x}"
            doc["time"] = t
            doc["diff"] = d
            ops.append(pymongo.InsertOne(doc))
            if max_batch_size and len(ops) >= max_batch_size:
                coll.bulk_write(ops)
                ops = []
        if ops:
            coll.bulk_write(ops)

    add_writer(table, on_batch, client.close)
