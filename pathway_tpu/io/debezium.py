"""pw.io.debezium — CDC change-stream ingestion.

TPU-native counterpart of the reference's DebeziumMessageParser
(reference: src/connectors/data_format.rs:1017 — parses Debezium
envelopes {before, after, op} with op in c/r/u/d, plus the MongoDB
dialect where `after` arrives as an embedded JSON string and deletes
carry only `before`/`filter`). Transport is pluggable: Kafka when a
client library exists (matching the reference's rdkafka transport),
or a directory of message files / a ConnectorSubject for testing.
"""

from __future__ import annotations

import json as _json
import os
import threading
from typing import Any

from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


def parse_debezium_message(
    payload: Any, column_names, schema, db_type: str = "postgres"
):
    """Parse one Debezium envelope -> list of (diff, values_tuple).
    (reference: DebeziumMessageParser::parse, data_format.rs:1017)"""
    if isinstance(payload, (bytes, str)):
        payload = _json.loads(payload)
    if payload is None:
        return []
    if "payload" in payload and isinstance(payload["payload"], dict):
        payload = payload["payload"]
    op = payload.get("op")
    dtypes = schema.dtypes() if schema else {}

    def vals_of(obj):
        if obj is None:
            return None
        if isinstance(obj, str) and db_type == "mongodb":
            obj = _json.loads(obj)
        out = []
        for c in column_names:
            v = obj.get(c)
            d = dtypes.get(c, dt.ANY).strip_optional()
            if d == dt.JSON and not isinstance(v, Json):
                v = Json(v)
            elif d == dt.FLOAT and isinstance(v, int):
                v = float(v)
            out.append(v)
        return tuple(out)

    before = vals_of(payload.get("before"))
    after = vals_of(payload.get("after"))
    events = []
    if op in ("c", "r"):  # create / snapshot read
        if after is not None:
            events.append((1, after))
    elif op == "u":
        if before is not None:
            events.append((-1, before))
        if after is not None:
            events.append((1, after))
    elif op == "d":
        if before is not None:
            events.append((-1, before))
        elif db_type == "mongodb" and payload.get("filter"):
            flt = payload["filter"]
            if isinstance(flt, str):
                flt = _json.loads(flt)
            events.append((-1, vals_of(flt)))
    return events


class _DirMessageSource(StreamingSource):
    """Reads Debezium JSON messages from files in a directory (one JSON per
    line) — the file-transport used by tests and replays."""

    def __init__(self, path, column_names, schema, pk_cols, db_type, refresh_s=0.2):
        super().__init__(column_names)
        self.path = path
        self.schema = schema
        self.pk_cols = pk_cols
        self.db_type = db_type
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread = None
        self._offsets: dict[str, int] = {}  # path -> lines consumed
        self._sigs: dict[str, tuple] = {}  # path -> (mtime, size) gate

    def offset_state(self) -> dict:
        return {"offsets": dict(self._offsets)}

    def seek(self, state: dict) -> None:
        self._offsets = dict(state.get("offsets", {}))

    def _key_for(self, vals):
        if self.pk_cols:
            return int(
                ref_scalar(
                    *[vals[self.column_names.index(c)] for c in self.pk_cols]
                )
            )
        return int(ref_scalar(*vals))

    def _scan(self):
        if not os.path.isdir(self.path):
            return
        for fname in sorted(os.listdir(self.path)):
            fpath = os.path.join(self.path, fname)
            try:
                st = os.stat(fpath)
            except OSError:
                continue
            if not os.path.isfile(fpath):
                continue
            sig = (st.st_mtime, st.st_size)
            if self._sigs.get(fpath) == sig:
                continue  # unchanged since last poll — skip the re-read
            self._sigs[fpath] = sig
            start = self._offsets.get(fpath, 0)
            try:
                with open(fpath) as f:
                    lines = f.readlines()
            except OSError:
                continue
            if len(lines) <= start:
                continue
            rows = []
            for line in lines[start:]:
                line = line.strip()
                if not line:
                    continue
                for diff, vals in parse_debezium_message(
                    line, self.column_names, self.schema, self.db_type
                ):
                    rows.append((self._key_for(vals), diff, vals))
            self._offsets[fpath] = len(lines)
            if rows:
                self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class _KafkaMessageSource(StreamingSource):  # pragma: no cover - needs broker
    def __init__(self, settings, topic, column_names, schema, pk_cols, db_type):
        super().__init__(column_names)
        from pathway_tpu.io._utils import require

        self._ck = require("confluent_kafka", "debezium")
        self.settings = settings
        self.topic = topic
        self.schema = schema
        self.pk_cols = pk_cols
        self.db_type = db_type
        self._stop = threading.Event()
        self._thread = None

    def _loop(self):
        consumer = self._ck.Consumer(self.settings)
        consumer.subscribe([self.topic])
        while not self._stop.is_set():
            msg = consumer.poll(0.2)
            if msg is None or msg.error():
                continue
            rows = []
            for diff, vals in parse_debezium_message(
                msg.value(), self.column_names, self.schema, self.db_type
            ):
                if self.pk_cols:
                    key = int(
                        ref_scalar(
                            *[
                                vals[self.column_names.index(c)]
                                for c in self.pk_cols
                            ]
                        )
                    )
                else:
                    key = int(ref_scalar(*vals))
                rows.append((key, diff, vals))
            if rows:
                self.session.insert_batch(rows)
        consumer.close()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


def read(
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    schema: Any,
    db_type: str = "postgres",
    input_dir: str | None = None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    column_names = list(schema.column_names())
    pk_cols = schema.primary_key_columns()
    if input_dir is not None:
        source: Any = _DirMessageSource(
            input_dir, column_names, schema, pk_cols, db_type
        )
    else:
        source = _KafkaMessageSource(
            rdkafka_settings, topic_name, column_names, schema, pk_cols, db_type
        )
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())
