"""pw.io.python — custom Python sources
(reference: python/pathway/io/python/__init__.py:47 ConnectorSubject +
Rust PythonReader, src/connectors/data_storage.rs:840)."""

from __future__ import annotations

import json as _json
import threading
from typing import Any, Sequence

from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar, sequential_key
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class ConnectorSubject:
    """Subclass and implement run(); call self.next(**values) /
    next_json / next_str / next_bytes; optionally self.commit()."""

    _session = None
    _column_names: Sequence[str] = ()
    _schema = None
    _counter = 0
    _deletions_enabled = True

    def run(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass

    @property
    def _with_metadata(self) -> bool:
        return False

    # --- feeding -------------------------------------------------------------

    def _key_for(self, values: dict) -> int:
        pk = self._schema.primary_key_columns() if self._schema else None
        if pk:
            return int(ref_scalar(*[values.get(c) for c in pk]))
        self._counter += 1
        return int(ref_scalar(id(self), self._counter))

    def _vals(self, values: dict) -> tuple:
        return tuple(values.get(c) for c in self._column_names)

    def next(self, **values: Any) -> None:
        assert self._session is not None
        coerced = self._coerce_values(values)
        self._session.insert(self._key_for(coerced), self._vals(coerced))

    def _coerce_values(self, values: dict) -> dict:
        if self._schema is None:
            return values
        out = dict(values)
        for name, d in self._schema.dtypes().items():
            if name in out:
                v = out[name]
                sd = d.strip_optional()
                if sd == dt.JSON and not (v is None and d.is_optional()):
                    from pathway_tpu.internals.json import normalize_json

                    out[name] = normalize_json(v)
                elif sd == dt.FLOAT and isinstance(v, int):
                    out[name] = float(v)
        return out

    def next_json(self, values: dict | str) -> None:
        if isinstance(values, str):
            values = _json.loads(values)
        self.next(**values)

    def next_str(self, message: str) -> None:
        self.next(data=message)

    def next_bytes(self, message: bytes) -> None:
        self.next(data=message)

    def _remove(self, key, values: dict) -> None:
        assert self._session is not None
        coerced = self._coerce_values(values)
        self._session.remove(int(key), self._vals(coerced))

    def commit(self) -> None:
        pass

    def close(self) -> None:
        assert self._session is not None
        self._session.close()


class _PythonSource(StreamingSource):
    def __init__(self, subject: ConnectorSubject, column_names, schema):
        super().__init__(column_names)
        self.subject = subject
        subject._session = self.session
        subject._column_names = column_names
        subject._schema = schema
        self._thread: threading.Thread | None = None

    def start(self):
        def runner():
            try:
                self.subject.run()
            finally:
                self.session.close()

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def stop(self):
        self.subject.on_stop()


def read(
    subject: ConnectorSubject,
    *,
    schema: Any = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    if schema is None:
        from pathway_tpu.internals.schema import schema_from_types

        schema = schema_from_types(data=bytes)
    column_names = list(schema.column_names())
    source = _PythonSource(subject, column_names, schema)
    node = InputNode(source, column_names)
    from pathway_tpu.internals import parse_graph

    parse_graph.G.streaming_sources.append(source)
    return Table._from_node(node, dict(schema.dtypes()), Universe())
