"""pw.io.s3 — object-store reader over fsspec.

TPU-native counterpart of the reference's S3 scanner
(reference: src/connectors/scanner/s3.rs:275 + posix_like.rs framework).
Uses fsspec's protocol registry: `s3://` paths need `s3fs` installed;
`file://`/`memory://` work out of the box (and are how tests exercise the
scanner). Polls the prefix for new/changed objects and streams diffs like
the fs connector.
"""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import require
from pathway_tpu.io.fs import _coerce_json_one, _coerce_one, _make_coercers


class AwsS3Settings:
    """(reference: python/pathway/io/s3 AwsS3Settings)"""

    def __init__(
        self,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
        **kwargs: Any,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style

    def storage_options(self) -> dict:
        opts: dict[str, Any] = {}
        if self.access_key:
            opts["key"] = self.access_key
        if self.secret_access_key:
            opts["secret"] = self.secret_access_key
        client_kwargs: dict[str, Any] = {}
        if self.region:
            client_kwargs["region_name"] = self.region
        if self.endpoint:
            client_kwargs["endpoint_url"] = self.endpoint
        if client_kwargs:
            opts["client_kwargs"] = client_kwargs
        if self.with_path_style:
            opts["config_kwargs"] = {"s3": {"addressing_style": "path"}}
        return opts


def _open_fs(path: str, settings: AwsS3Settings | None):
    fsspec = require("fsspec", "s3")
    protocol = path.split("://", 1)[0] if "://" in path else "file"
    opts = settings.storage_options() if settings else {}
    return fsspec.filesystem(protocol, **opts), protocol


def _parse_object(data: bytes, opath: str, format: str, schema, column_names):
    """bytes -> [(pk_tuple, values)] — same formats as the fs connector."""
    import csv as _csv
    import io
    import json as _json

    if format in ("plaintext", "plaintext_by_file"):
        text = data.decode("utf-8", errors="replace")
        if format == "plaintext_by_file":
            return [((opath,), (text,))]
        return [
            ((opath, i), (line,))
            for i, line in enumerate(text.splitlines())
        ]
    if format == "binary":
        return [((opath,), (data,))]
    out = []
    if format == "csv":
        coercers = _make_coercers(schema, list(column_names), _coerce_one)
        reader = _csv.DictReader(io.StringIO(data.decode("utf-8", errors="replace")))
        for i, row in enumerate(reader):
            if coercers is not None:
                vals = tuple(fn(row.get(n)) for n, fn in coercers)
            else:
                vals = tuple(row.get(n) for n in column_names)
            out.append(((opath, i), vals))
        return out
    if format in ("json", "jsonlines"):
        coercers = _make_coercers(schema, list(column_names), _coerce_json_one)
        for i, line in enumerate(data.decode("utf-8", errors="replace").splitlines()):
            line = line.strip()
            if not line:
                continue
            obj = _json.loads(line)
            if coercers is not None:
                vals = tuple(fn(obj.get(n)) for n, fn in coercers)
            else:
                vals = tuple(obj.get(n) for n in column_names)
            out.append(((opath, i), vals))
        return out
    raise ValueError(f"unknown format {format!r}")


def _rows_for_object(fs, opath, format, schema, column_names, pk_cols,
                     cache=None, sig=None):
    data = None
    if cache is not None and sig is not None:
        # download-once: an object whose (mtime, size) signature matches
        # the cached version is served from the local blob cache
        # (reference: cached_object_storage.rs download-once semantics)
        meta = cache.metadata(opath)
        if meta is not None and meta.get("sig") == list(sig):
            data = cache.get(opath)
    if data is None:
        with fs.open(opath, "rb") as f:
            data = f.read()
        if cache is not None:
            cache.upsert(opath, data, {"sig": list(sig) if sig else None})
    rows = []
    for pk, vals in _parse_object(data, opath, format, schema, column_names):
        if pk_cols:
            key = int(
                ref_scalar(*[vals[column_names.index(c)] for c in pk_cols])
            )
        else:
            key = int(ref_scalar(*pk))
        rows.append((key, vals))
    return rows


class _S3StaticSource(StaticSource):
    def __init__(self, path, settings, format, schema, column_names, pk_cols,
                 object_cache=None):
        super().__init__(column_names)
        self.path = path
        self.settings = settings
        self.format = format
        self.schema = schema
        self.pk_cols = pk_cols
        self.object_cache = object_cache

    def events(self):
        fs, _ = _open_fs(self.path, self.settings)
        rows = []
        for opath in sorted(fs.find(self.path)):
            sig = None
            if self.object_cache is not None:
                try:
                    info = fs.info(opath)
                    sig = (
                        str(info.get("mtime", info.get("LastModified", ""))),
                        info.get("size"),
                    )
                except OSError:
                    pass
            rows.extend(
                (k, 1, v)
                for k, v in _rows_for_object(
                    fs, opath, self.format, self.schema, self.column_names,
                    self.pk_cols, cache=self.object_cache, sig=sig,
                )
            )
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _S3StreamingSource(StreamingSource):
    def __init__(
        self, path, settings, format, schema, column_names, pk_cols,
        refresh_s=1.0, object_cache=None,
    ):
        super().__init__(column_names)
        self.path = path
        self.settings = settings
        self.format = format
        self.schema = schema
        self.pk_cols = pk_cols
        self.refresh_s = refresh_s
        self.object_cache = object_cache
        self._stop = threading.Event()
        self._thread = None
        self._seen: dict[str, Any] = {}
        self._emitted: dict[str, list] = {}

    def offset_state(self) -> dict:
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))

    def _scan(self, fs):
        for opath in sorted(fs.find(self.path)):
            try:
                info = fs.info(opath)
            except OSError:
                continue
            sig = (str(info.get("mtime", info.get("LastModified", ""))), info.get("size"))
            if self._seen.get(opath) == sig:
                continue
            rows = [
                (k, -1, v) for k, v in self._emitted.get(opath, [])
            ]
            try:
                new = _rows_for_object(
                    fs, opath, self.format, self.schema, self.column_names,
                    self.pk_cols, cache=self.object_cache, sig=sig,
                )
            except OSError:
                continue
            rows.extend((k, 1, v) for k, v in new)
            self._seen[opath] = sig
            self._emitted[opath] = new
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        fs, _ = _open_fs(self.path, self.settings)
        scans = 0
        while not self._stop.is_set():
            try:
                self._scan(fs)
            except OSError:
                pass
            scans += 1
            if self.object_cache is not None and scans % 60 == 0:
                # bound cache growth: superseded object versions pile up
                # one per change otherwise
                try:
                    self.object_cache.vacuum()
                except OSError:
                    pass
            self._stop.wait(self.refresh_s)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "csv",
    schema: Any = None,
    mode: str = "streaming",
    name: str | None = None,
    persistent_id: str | None = None,
    object_cache: Any = None,
    **kwargs: Any,
) -> Table:
    """``object_cache`` — a persistence Backend or CachedObjectStorage:
    unchanged objects are served from the local versioned blob cache
    instead of being re-downloaded (reference: cached_object_storage.rs)."""
    if object_cache is not None:
        from pathway_tpu.persistence.cached_object_storage import (
            CachedObjectStorage,
        )

        if not isinstance(object_cache, CachedObjectStorage):
            object_cache = CachedObjectStorage(object_cache)
    if format in ("plaintext", "plaintext_by_file"):
        column_names = ["data"]
        dtypes = {"data": dt.STR}
        schema_ = None
    elif format == "binary":
        column_names = ["data"]
        dtypes = {"data": dt.BYTES}
        schema_ = None
    else:
        assert schema is not None, f"schema required for format {format!r}"
        column_names = list(schema.column_names())
        dtypes = dict(schema.dtypes())
        schema_ = schema
    pk_cols = schema_.primary_key_columns() if schema_ else None
    if mode == "static":
        source: Any = _S3StaticSource(
            path, aws_s3_settings, format, schema_, column_names, pk_cols,
            object_cache=object_cache,
        )
    else:
        source = _S3StreamingSource(
            path, aws_s3_settings, format, schema_, column_names, pk_cols,
            object_cache=object_cache,
        )
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dtypes, Universe())
