"""pw.io.s3_csv — CSV-over-S3 convenience wrapper
(reference: python/pathway/io/s3_csv wraps io/s3 with format=csv)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io.s3 import AwsS3Settings, read as _s3_read


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    schema: Any = None,
    mode: str = "streaming",
    **kwargs: Any,
):
    return _s3_read(
        path,
        aws_s3_settings=aws_s3_settings,
        format="csv",
        schema=schema,
        mode=mode,
        **kwargs,
    )
