"""pw.io.gdrive — Google Drive source
(reference: python/pathway/io/gdrive — polls a folder for file
changes via the Drive v3 API and streams object bytes + metadata).
Requires google-api-python-client at call time."""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import require


class _GDriveSource(StreamingSource):  # pragma: no cover - needs API creds
    def __init__(self, object_id, credentials_file, refresh_interval, with_metadata):
        super().__init__(["data", "_metadata"] if with_metadata else ["data"])
        require("googleapiclient", "gdrive")
        self.object_id = object_id
        self.credentials_file = credentials_file
        self.refresh_interval = refresh_interval
        self.with_metadata = with_metadata
        self._stop = threading.Event()
        self._thread = None
        self._seen: dict[str, str] = {}  # file id -> modifiedTime

    def offset_state(self) -> dict:
        return {"seen": dict(self._seen)}

    def seek(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))

    def _service(self):
        from google.oauth2.service_account import Credentials  # type: ignore
        from googleapiclient.discovery import build  # type: ignore

        creds = Credentials.from_service_account_file(
            self.credentials_file,
            scopes=["https://www.googleapis.com/auth/drive.readonly"],
        )
        return build("drive", "v3", credentials=creds)

    def _loop(self):
        service = self._service()
        while not self._stop.is_set():
            resp = (
                service.files()
                .list(
                    q=f"'{self.object_id}' in parents and trashed = false",
                    fields="files(id, name, modifiedTime, mimeType)",
                )
                .execute()
            )
            rows = []
            for f in resp.get("files", []):
                if self._seen.get(f["id"]) == f["modifiedTime"]:
                    continue
                data = service.files().get_media(fileId=f["id"]).execute()
                self._seen[f["id"]] = f["modifiedTime"]
                key = int(ref_scalar(f["id"]))
                if self.with_metadata:
                    rows.append((key, 1, (data, Json(f))))
                else:
                    rows.append((key, 1, (data,)))
            if rows:
                self.session.insert_batch(rows, self.offset_state())
            self._stop.wait(self.refresh_interval)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    service_user_credentials_file: str,
    with_metadata: bool = False,
    refresh_interval: int = 30,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    source = _GDriveSource(
        object_id, service_user_credentials_file, refresh_interval, with_metadata
    )
    node = InputNode(source, source.column_names)
    dtypes: dict[str, Any] = {"data": dt.BYTES}
    if with_metadata:
        dtypes["_metadata"] = dt.JSON
    return Table._from_node(node, dtypes, Universe())
