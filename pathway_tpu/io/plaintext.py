"""pw.io.plaintext (reference: python/pathway/io/plaintext)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs as _fs


def read(
    path: str,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
):
    return _fs.read(
        path,
        format="plaintext",
        mode=mode,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name,
        **kwargs,
    )
