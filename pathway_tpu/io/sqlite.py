"""pw.io.sqlite — SQLite reader/writer.

TPU-native counterpart of the reference's native SqliteReader
(reference: src/connectors/data_storage.rs:1534 — snapshots the table and
streams changes by polling SQLite's `PRAGMA data_version` and diffing
against the previously observed state). The writer applies diff batches
transactionally (insert on +1, delete on -1).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import ref_scalar
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable


def _coerce(v: Any, d) -> Any:
    """sqlite-specific coercion: BOOL arrives as 0/1, BYTES may arrive as
    TEXT (the fs connector's _coerce handles string-typed inputs instead)."""
    if v is None:
        return None
    sd = d.strip_optional()
    try:
        if sd == dt.INT:
            return int(v)
        if sd == dt.FLOAT:
            return float(v)
        if sd == dt.BOOL:
            return bool(v)
        if sd == dt.STR:
            return str(v)
        if sd == dt.BYTES:
            return v if isinstance(v, bytes) else str(v).encode()
        if sd == dt.JSON:
            import json as _json

            return Json(_json.loads(v) if isinstance(v, str) else v)
    except (ValueError, TypeError):
        return None
    return v


def _snapshot(
    conn: sqlite3.Connection, table_name: str, column_names, schema
) -> dict[int, tuple]:
    cols = ", ".join(f'"{c}"' for c in column_names)
    cur = conn.execute(f'SELECT {cols} FROM "{table_name}"')  # noqa: S608
    dtypes = schema.dtypes() if schema else {}
    pk_cols = schema.primary_key_columns() if schema else None
    rows: dict[int, tuple] = {}
    for i, raw in enumerate(cur.fetchall()):
        vals = tuple(
            _coerce(v, dtypes.get(c, dt.ANY))
            for c, v in zip(column_names, raw)
        )
        if pk_cols:
            key = int(
                ref_scalar(*[vals[column_names.index(c)] for c in pk_cols])
            )
        else:
            key = int(ref_scalar(*vals))
        rows[key] = vals
    return rows


class _SqliteStaticSource(StaticSource):
    def __init__(self, path, table_name, column_names, schema):
        super().__init__(column_names)
        self.path = path
        self.table_name = table_name
        self.schema = schema

    def events(self):
        conn = sqlite3.connect(self.path)
        try:
            rows = _snapshot(conn, self.table_name, self.column_names, self.schema)
        finally:
            conn.close()
        if rows:
            yield 0, DiffBatch.from_rows(
                [(k, 1, v) for k, v in rows.items()], self.column_names
            )


class _SqliteStreamingSource(StreamingSource):
    """Poll data_version; on change, diff the table snapshot and emit
    insert/delete rows (the reference reader does the same state diffing)."""

    def __init__(self, path, table_name, column_names, schema, refresh_s=0.2):
        super().__init__(column_names)
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._state: dict[int, tuple] = {}
        self._data_version: int | None = None

    def offset_state(self) -> dict:
        return {"state": dict(self._state)}

    def seek(self, state: dict) -> None:
        self._state = dict(state.get("state", {}))

    def _poll(self, conn):
        ver = conn.execute("PRAGMA data_version").fetchone()[0]
        count = conn.execute(
            f'SELECT COUNT(*) FROM "{self.table_name}"'  # noqa: S608
        ).fetchone()[0]
        sig = (ver, count)
        if sig == self._data_version:
            return
        self._data_version = sig
        new = _snapshot(conn, self.table_name, self.column_names, self.schema)
        rows = []
        for k, vals in self._state.items():
            if k not in new:
                rows.append((k, -1, vals))
            elif new[k] != vals:
                rows.append((k, -1, vals))
        for k, vals in new.items():
            old = self._state.get(k)
            if old is None or old != vals:
                rows.append((k, 1, vals))
        self._state = new
        if rows:
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        conn = sqlite3.connect(self.path)
        try:
            while not self._stop.is_set():
                try:
                    self._poll(conn)
                except sqlite3.Error:
                    pass
                self._stop.wait(self.refresh_s)
        finally:
            conn.close()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def read(
    path: str,
    table_name: str,
    schema: Any,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    column_names = list(schema.column_names())
    if mode == "static":
        source: Any = _SqliteStaticSource(path, table_name, column_names, schema)
    else:
        source = _SqliteStreamingSource(path, table_name, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dict(schema.dtypes()), Universe())


def write(table: Table, path: str, table_name: str, **kwargs: Any) -> None:
    """Apply the output diff stream to a SQLite table transactionally."""
    column_names = table.column_names()
    state = {"conn": None}

    def _conn() -> sqlite3.Connection:
        if state["conn"] is None:
            conn = sqlite3.connect(path, check_same_thread=False)
            cols = ", ".join(f'"{c}"' for c in column_names)
            conn.execute(
                f'CREATE TABLE IF NOT EXISTS "{table_name}" '
                f"({cols}, __key__ INTEGER PRIMARY KEY)"
            )
            state["conn"] = conn
        return state["conn"]

    def on_batch(t: int, batch: DiffBatch) -> None:
        conn = _conn()
        placeholders = ", ".join("?" for _ in column_names) + ", ?"
        with conn:
            for k, d, vals in batch.iter_rows():
                # sqlite ints are signed 64-bit
                skey = k - (1 << 64) if k >= 1 << 63 else k
                if d > 0:
                    conn.execute(
                        f'INSERT OR REPLACE INTO "{table_name}" VALUES '  # noqa: S608
                        f"({placeholders})",
                        tuple(jsonable_sql(v) for v in vals) + (skey,),
                    )
                else:
                    conn.execute(
                        f'DELETE FROM "{table_name}" WHERE __key__ = ?',  # noqa: S608
                        (skey,),
                    )

    def on_end() -> None:
        if state["conn"] is not None:
            state["conn"].close()

    add_writer(table, on_batch, on_end)


def jsonable_sql(v: Any) -> Any:
    v = jsonable(v)
    if isinstance(v, (dict, list)):
        import json as _json

        return _json.dumps(v)
    return v
