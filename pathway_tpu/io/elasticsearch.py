"""pw.io.elasticsearch — Elasticsearch sink via the REST bulk API.

TPU-native counterpart of the reference's ElasticSearchWriter
(reference: src/connectors/data_storage.rs:1451). Speaks the `_bulk`
HTTP/JSON protocol directly with `requests`, so no elasticsearch client
package is needed: +1 diffs become `index` actions keyed by the row key,
-1 diffs become `delete` actions.
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io._utils import add_writer, row_dicts


class ElasticSearchAuth:
    def __init__(self, kind: str, **kw: Any):
        self.kind = kind
        self.kw = kw

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", username=username, password=password)

    @classmethod
    def apikey(cls, api_key_id: str, api_key: str) -> "ElasticSearchAuth":
        return cls("apikey", api_key_id=api_key_id, api_key=api_key)

    def apply(self, session) -> None:
        if self.kind == "basic":
            session.auth = (self.kw["username"], self.kw["password"])
        elif self.kind == "apikey":
            import base64

            token = base64.b64encode(
                f"{self.kw['api_key_id']}:{self.kw['api_key']}".encode()
            ).decode()
            session.headers["Authorization"] = f"ApiKey {token}"


def write(
    table,
    host: str,
    auth: ElasticSearchAuth | None = None,
    index_name: str = "pathway",
    *,
    max_batch_size: int | None = None,
    **kwargs: Any,
) -> None:
    import requests

    column_names = table.column_names()
    session = requests.Session()
    if auth is not None:
        auth.apply(session)

    def on_batch(t: int, batch: DiffBatch) -> None:
        lines: list[str] = []
        for k, d, doc in row_dicts(batch, column_names, t):
            doc_id = f"{k:016x}"
            if d > 0:
                lines.append(
                    _json.dumps(
                        {"index": {"_index": index_name, "_id": doc_id}}
                    )
                )
                lines.append(_json.dumps(doc))
            else:
                lines.append(
                    _json.dumps(
                        {"delete": {"_index": index_name, "_id": doc_id}}
                    )
                )
            if max_batch_size and len(lines) >= max_batch_size * 2:
                _flush(lines)
                lines = []
        if lines:
            _flush(lines)

    def _flush(lines: list[str]) -> None:
        body = "\n".join(lines) + "\n"
        resp = session.post(
            host.rstrip("/") + "/_bulk",
            data=body.encode(),
            headers={"Content-Type": "application/x-ndjson"},
            timeout=30,
        )
        resp.raise_for_status()
        # ES reports per-item failures with HTTP 200 + errors:true
        result = resp.json()
        if result.get("errors"):
            failed = [
                item
                for item in result.get("items", [])
                for op in item.values()
                if op.get("error")
            ]
            raise RuntimeError(f"elasticsearch bulk errors: {failed[:5]}")

    add_writer(table, on_batch)
