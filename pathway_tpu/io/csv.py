"""pw.io.csv (reference: python/pathway/io/csv)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs as _fs


class CsvParserSettings:
    def __init__(
        self,
        delimiter: str = ",",
        quote: str = '"',
        escape: str | None = None,
        enable_double_quote_escapes: bool = True,
        enable_quoting: bool = True,
        comment_character: str | None = None,
    ):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.enable_double_quote_escapes = enable_double_quote_escapes
        self.enable_quoting = enable_quoting
        self.comment_character = comment_character


def read(
    path: str,
    *,
    schema: Any = None,
    csv_settings: CsvParserSettings | None = None,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
):
    return _fs.read(
        path,
        format="csv",
        schema=schema,
        csv_settings=csv_settings,
        mode=mode,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name,
        **kwargs,
    )


def write(table, filename: str, *, name: str | None = None, **kwargs) -> None:
    _fs.write(table, filename, format="csv", **kwargs)
