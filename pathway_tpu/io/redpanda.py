"""pw.io.redpanda — Redpanda speaks the Kafka protocol; same connector
(reference: python/pathway/io/redpanda wraps io/kafka)."""

from pathway_tpu.io.kafka import read, write

__all__ = ["read", "write"]
