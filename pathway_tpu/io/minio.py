"""pw.io.minio — MinIO is S3-compatible; same scanner with a custom
endpoint (reference: python/pathway/io/minio wraps io/s3)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io.s3 import AwsS3Settings, read as _s3_read


class MinIOSettings:
    def __init__(
        self,
        endpoint: str,
        bucket_name: str,
        access_key: str,
        secret_access_key: str,
        *,
        with_path_style: bool = True,
        region: str | None = None,
        **kwargs: Any,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            region=self.region,
            endpoint=self.endpoint,
            with_path_style=self.with_path_style,
        )


def read(
    path: str,
    minio_settings: MinIOSettings,
    *,
    format: str = "csv",
    schema: Any = None,
    mode: str = "streaming",
    **kwargs: Any,
):
    return _s3_read(
        path,
        aws_s3_settings=minio_settings.create_aws_settings(),
        format=format,
        schema=schema,
        mode=mode,
        **kwargs,
    )
