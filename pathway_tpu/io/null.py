"""pw.io.null — sink that discards rows while still forcing computation
(reference: NullWriter, src/connectors/data_storage.rs:1514)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._utils import add_writer


def write(table, *args: Any, **kwargs: Any) -> None:
    add_writer(table, lambda t, batch: None)
