"""pw.io.nats — NATS source/sink (reference: NatsReader/NatsWriter,
src/connectors/data_storage.rs:1775,1845). Requires `nats-py` at call
time."""

from __future__ import annotations

import json as _json
import threading
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._utils import add_writer, jsonable, require
from pathway_tpu.io.kafka import _parse_message


class _NatsSource(StreamingSource):
    def __init__(self, uri, topic, format, column_names, schema):
        super().__init__(column_names)
        require("nats", "nats")
        self.uri = uri
        self.topic = topic
        self.format = format
        self.schema = schema
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _loop(self):
        import asyncio
        import itertools

        import nats

        counter = itertools.count()

        async def run():
            nc = await nats.connect(self.uri)
            sub = await nc.subscribe(self.topic)
            while not self._stop.is_set():
                try:
                    msg = await sub.next_msg(timeout=0.2)
                except Exception:
                    continue
                rows = [
                    (key, 1, vals)
                    for key, vals in _parse_message(
                        msg.data, self.format, self.column_names, self.schema,
                        counter,
                    )
                ]
                self.session.insert_batch(rows)
            await nc.close()

        asyncio.run(run())

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


def read(
    uri: str,
    topic: str,
    *,
    schema: Any = None,
    format: str = "raw",
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format in ("raw", "plaintext"):
        column_names = ["data"]
        dtypes = {"data": dt.BYTES if format == "raw" else dt.STR}
    else:
        assert schema is not None
        column_names = list(schema.column_names())
        dtypes = dict(schema.dtypes())
    source = _NatsSource(uri, topic, format, column_names, schema)
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dtypes, Universe())


def write(
    table: Table, uri: str, topic: str, *, format: str = "json", **kwargs: Any
) -> None:
    require("nats", "nats")
    import asyncio

    import nats

    column_names = table.column_names()
    state: dict[str, Any] = {"loop": None, "nc": None}

    def _ensure():
        if state["loop"] is None:
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever, daemon=True)
            t.start()
            state["loop"] = loop
            fut = asyncio.run_coroutine_threadsafe(nats.connect(uri), loop)
            state["nc"] = fut.result(timeout=10)
        return state["loop"], state["nc"]

    def on_batch(t: int, batch: DiffBatch) -> None:
        loop, nc = _ensure()
        for k, d, vals in batch.iter_rows():
            payload = {n: jsonable(v) for n, v in zip(column_names, vals)}
            payload["time"] = t
            payload["diff"] = d
            asyncio.run_coroutine_threadsafe(
                nc.publish(topic, _json.dumps(payload).encode()), loop
            ).result(timeout=10)

    add_writer(table, on_batch)
