"""Shared connector plumbing: output-node registration + row conversion
(reference analog: src/connectors/data_format.rs Formatter machinery —
formatters turn diff rows into sink payloads)."""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import OutputNode
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.json import Json


def jsonable(v: Any) -> Any:
    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    if isinstance(v, tuple):
        return [jsonable(x) for x in v]
    return v


def row_dicts(batch: DiffBatch, column_names: Sequence[str], t: int):
    """Yield (key, diff, {col: jsonable}) per row."""
    for k, d, vals in batch.iter_rows():
        yield k, d, {n: jsonable(v) for n, v in zip(column_names, vals)}


def add_writer(
    table,
    on_batch: Callable[[int, DiffBatch], None],
    on_end: Callable[[], None] | None = None,
) -> None:
    node = OutputNode(table._node, on_batch, on_end)
    parse_graph.G.add_output(node)


def require(module_name: str, connector: str, hint: str | None = None):
    """Lazy client-library import with a actionable error
    (the image gates which service SDKs exist; connectors degrade to a
    clear message, not a crash at import time)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        msg = (
            f"pw.io.{connector} requires the {module_name!r} package, which "
            f"is not installed in this environment."
        )
        if hint:
            msg += " " + hint
        raise ImportError(msg) from e
