"""HTTP client connector: poll/stream an endpoint into a table; write rows
out as HTTP requests (reference: python/pathway/io/http read/write)."""

from __future__ import annotations

import json as _json
import time
from typing import Any, Sequence

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import OutputNode
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.internals.table import Table
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def read(
    url: str,
    *,
    schema: Any = None,
    method: str = "GET",
    payload: Any = None,
    headers: dict[str, str] | None = None,
    format: str = "json",
    refresh_interval_ms: int = 10000,
    n_retries: int = 0,
    mode: str = "streaming",
    **kwargs: Any,
) -> Table:
    if schema is None:
        schema = schema_from_types(data=bytes)

    class HttpSubject(ConnectorSubject):
        def run(self) -> None:
            import requests

            while True:
                try:
                    resp = requests.request(
                        method, url, json=payload, headers=headers, timeout=30
                    )
                    if format == "json":
                        data = resp.json()
                        rows = data if isinstance(data, list) else [data]
                        for row in rows:
                            self.next(**row)
                    else:
                        self.next(data=resp.content)
                except Exception:
                    pass
                if mode == "static":
                    break
                time.sleep(refresh_interval_ms / 1000.0)

    return python_read(HttpSubject(), schema=schema)


def write(
    table: Table,
    url: str,
    *,
    method: str = "POST",
    format: str = "json",
    request_payload_template: Any = None,
    n_retries: int = 0,
    headers: dict[str, str] | None = None,
    **kwargs: Any,
) -> None:
    col_names = table.column_names()

    def on_batch(t: int, batch: DiffBatch) -> None:
        import requests

        for k, d, vals in batch.iter_rows():
            if d <= 0:
                continue
            payload = dict(zip(col_names, vals))
            for attempt in range(n_retries + 1):
                try:
                    requests.request(
                        method, url, json=payload, headers=headers, timeout=30
                    )
                    break
                except Exception:
                    if attempt == n_retries:
                        pass

    node = OutputNode(table._node, on_batch)
    parse_graph.G.add_output(node)
