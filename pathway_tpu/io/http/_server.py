"""REST server connector: HTTP requests become table rows; responses are
delivered when the result row for the request id arrives
(reference: python/pathway/io/http/_server.py — PathwayWebserver:329 with
OpenAPI docgen:126, RestServerSubject:490, rest_connector:624)."""

from __future__ import annotations

import asyncio
import json as _json
import threading
import time as _time
import uuid
from typing import Any, Mapping, Sequence

from aiohttp import web

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import OutputNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import Pointer, ref_scalar
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.schema import SchemaMetaclass, schema_from_types
from pathway_tpu.internals.table import Table
from pathway_tpu.io.python import ConnectorSubject, read as python_read


class EndpointDocumentation:
    def __init__(
        self,
        summary: str | None = None,
        description: str | None = None,
        tags: Sequence[str] | None = None,
        method_status: Any = None,
        **kwargs,
    ):
        self.summary = summary
        self.description = description
        self.tags = list(tags or [])


class EndpointExamples:
    def __init__(self):
        self.examples: list = []

    def add_example(self, *args, **kwargs):
        return self


class PathwayWebserver:
    """One aiohttp server shared by all rest_connector routes."""

    def __init__(
        self,
        host: str,
        port: int,
        with_schema_endpoint: bool = True,
        with_cors: bool = False,
    ):
        self.host = host
        self.port = port
        self._app = web.Application()
        self._routes: dict[str, Any] = {}
        self._openapi: dict[str, Any] = {
            "openapi": "3.0.3",
            "info": {"title": "Pathway-TPU API", "version": "1.0"},
            "paths": {},
        }
        self._started = False
        self._stopped = False
        self._lock = threading.Lock()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_ready = threading.Event()
        self._stop_async: Any = None  # threadsafe resolver of the stop event
        self._thread: threading.Thread | None = None
        self._runner: web.AppRunner | None = None
        self._gates: list[Any] = []  # SurgeGates of this server's routes
        if with_schema_endpoint:
            self._app.router.add_get("/_schema", self._schema_handler)

    async def _schema_handler(self, request: web.Request) -> web.Response:
        return web.json_response(self._openapi)

    def _register_endpoint(
        self, route: str, handler, methods: Sequence[str], schema, documentation
    ) -> None:
        with self._lock:
            resource = self._routes.get(route)
            if resource is None:
                resource = self._app.router.add_resource(route)
                self._routes[route] = resource
            for method in methods:
                resource.add_route(method, handler)
            doc: dict[str, Any] = {}
            for method in methods:
                entry: dict[str, Any] = {
                    "responses": {"200": {"description": "OK"}}
                }
                if documentation is not None:
                    if documentation.summary:
                        entry["summary"] = documentation.summary
                    if documentation.description:
                        entry["description"] = documentation.description
                    if documentation.tags:
                        entry["tags"] = documentation.tags
                if schema is not None:
                    props = {
                        name: {"type": _openapi_type(c.dtype)}
                        for name, c in schema.columns().items()
                    }
                    entry["requestBody"] = {
                        "content": {
                            "application/json": {
                                "schema": {
                                    "type": "object",
                                    "properties": props,
                                }
                            }
                        }
                    }
                doc[method.lower()] = entry
            self._openapi["paths"][route] = doc

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True

        def run_loop():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            stop_ev = asyncio.Event()
            self._stop_async = lambda: loop.call_soon_threadsafe(stop_ev.set)
            self._loop_ready.set()

            async def main():
                # short shutdown_timeout: stop() must not hang behind a
                # stuck keep-alive connection (drain already waited for
                # the responses that matter)
                runner = web.AppRunner(self._app, shutdown_timeout=1.0)
                self._runner = runner
                await runner.setup()
                site = web.TCPSite(runner, self.host, self.port)
                await site.start()
                # serve until stop(); a stop that landed while the site
                # was coming up (including one whose _loop_ready wait
                # timed out, so _stop_async was never called) skips the
                # wait — startup is never interrupted mid-await, and
                # cleanup always releases sockets + pending handlers
                if not self._stopped:
                    await stop_ev.wait()
                await runner.cleanup()

            try:
                loop.run_until_complete(main())
            finally:
                loop.close()

        self._thread = threading.Thread(target=run_loop, daemon=True)
        self._thread.start()

    def register_gate(self, gate: Any) -> None:
        with self._lock:
            self._gates.append(gate)

    def drain(self, grace_s: float | None = None) -> bool:
        """Graceful shutdown: every attached SurgeGate stops admitting
        (503 + Retry-After), flushes its queue, and waits for in-flight
        responses; then the listener closes. Returns True if all gates
        went idle within their grace period."""
        with self._lock:
            gates = list(self._gates)
        all_idle = True
        for gate in gates:
            all_idle = gate.drain(grace_s) and all_idle
        for gate in gates:
            gate.close()
        self.stop()
        return all_idle

    def stop(self, timeout: float = 5.0) -> None:
        """Close the listener and join the server thread (idempotent).
        In-flight aiohttp handlers are cancelled by runner.cleanup()."""
        with self._lock:
            if not self._started or self._stopped:
                return
            self._stopped = True
        # stop() can race the server thread's startup: wait until the
        # loop exists (the ready event is set before any aiohttp setup
        # work, so this wait is bounded by loop creation alone), then
        # resolve the async stop event — it lands whether main() is
        # still starting up or already serving.
        self._loop_ready.wait(timeout)
        stop_async = self._stop_async
        if stop_async is not None:
            try:
                stop_async()
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(timeout)


def _openapi_type(d: dt.DType) -> str:
    sd = d.strip_optional()
    if sd == dt.INT:
        return "integer"
    if sd == dt.FLOAT:
        return "number"
    if sd == dt.BOOL:
        return "boolean"
    if sd == dt.JSON:
        return "object"
    return "string"


class RestServerSubject(ConnectorSubject):
    """Feeds HTTP requests into the graph; resolves response futures when the
    response writer delivers results (reference: _server.py:490)."""

    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        schema: SchemaMetaclass,
        methods: Sequence[str],
        delete_completed_queries: bool,
        format: str = "raw",
        documentation: EndpointDocumentation | None = None,
        qos: Any = None,
    ):
        self._webserver = webserver
        self._route = route
        self._format = format
        self._request_schema = schema
        self._delete_completed = delete_completed_queries
        self._qos = qos  # serving.QoSConfig | None (None = ungated seed path)
        self._gate: Any = None  # SurgeGate, built in run() once the
        # InputSession exists
        self._stop_event = threading.Event()
        self._futures: dict[int, asyncio.Future] = {}
        self._futures_lock = threading.Lock()
        # Flight Recorder: serving-path latency, request-in to
        # response-out (covers the whole dataflow round trip, which is
        # what a client experiences), labeled by route
        from pathway_tpu.observability import REGISTRY

        self._m_seconds = REGISTRY.histogram(
            "pathway_rest_request_seconds",
            "REST request latency: ingestion to delivered response",
            labelnames=("route",),
        ).labels(route)
        self._m_requests = REGISTRY.counter(
            "pathway_rest_requests_total",
            "REST requests served, by route/method/status",
            labelnames=("route", "method", "status"),
        )
        self._m_inflight = REGISTRY.gauge(
            "pathway_rest_inflight_requests",
            "requests currently awaiting their dataflow result",
            labelnames=("route",),
        ).labels(route)
        webserver._register_endpoint(
            route, self._handle, methods, schema, documentation
        )
        self._ready = threading.Event()

    def run(self) -> None:
        if self._qos is not None:
            # Surge Gate: the QoS layer between this endpoint and the
            # engine tick. Built here (not __init__) because it feeds
            # the connector's InputSession, which exists only once the
            # runtime wires the source.
            from pathway_tpu.serving import SurgeGate

            self._gate = SurgeGate(
                self._qos,
                self._session,
                route=self._route,
                webserver=self._webserver,
            )
            self._webserver.register_gate(self._gate)
        self._webserver.start()
        self._ready.set()
        # stay alive for the lifetime of the graph (on_stop releases us)
        self._stop_event.wait()

    def on_stop(self) -> None:
        """Runtime stop: fail queued requests, close the gate, shut the
        webserver down so tests (and drains) don't leak servers."""
        if self._gate is not None:
            try:
                self._gate.close()
            except Exception:
                pass
        try:
            self._webserver.stop()
        except Exception:
            pass
        self._stop_event.set()

    async def _handle(self, request: web.Request) -> web.Response:
        from pathway_tpu.observability import tracing

        t0 = _time.perf_counter()
        self._m_inflight.inc()
        # Trace Weaver ingress: continue the caller's W3C trace when a
        # `traceparent` header arrives (the cross-service contract the
        # reference keeps across the Python/engine boundary,
        # python_api.rs:3343), mint a fresh root otherwise. The span
        # covers the whole dataflow round trip; the engine tick adopts
        # this context via the pending-request registry, so embed/KNN/
        # operator spans downstream share the trace id.
        span = tracing.get_tracer().span(
            "http.request",
            parent=tracing.parse_traceparent(
                request.headers.get("traceparent")
            ),
            root=True,
            ingress=True,
            route=self._route,
            method=request.method,
        )
        try:
            with span:
                response = await self._handle_inner(request)
                span.set_attribute("status", response.status)
        except Exception:
            self._m_requests.labels(
                self._route, request.method, "500"
            ).inc()
            raise
        finally:
            self._m_inflight.dec()
            self._m_seconds.observe(
                _time.perf_counter() - t0, exemplar=span.trace_id
            )
        self._m_requests.labels(
            self._route, request.method, str(response.status)
        ).inc()
        if span.context is not None:
            # echo the trace identity so callers can find this request in
            # /debug/trace (response contract: same trace id, our span id)
            response.headers["traceparent"] = span.context.traceparent()
        return response

    async def _handle_inner(self, request: web.Request) -> web.Response:
        from pathway_tpu.observability import tracing

        rid = uuid.uuid4().hex
        key = int(ref_scalar(rid))
        if self._format == "raw":
            body = await request.text()
            values: dict[str, Any] = {"query": body}
        else:
            try:
                payload = await request.json()
            except ValueError:
                payload = {}
            if request.rel_url.query:
                payload = {**dict(request.rel_url.query), **payload}
            values = {}
            for name, col in self._request_schema.columns().items():
                if name in payload:
                    values[name] = payload[name]
                elif col.has_default_value:
                    values[name] = col.default_value
                else:
                    return web.json_response(
                        {"error": f"missing field {name!r}"}, status=400
                    )
        coerced = self._coerce_values(values)
        vals = self._vals(coerced)
        assert self._session is not None
        if self._gate is not None:
            return await self._handle_gated(request, key, vals, coerced)
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        with self._futures_lock:
            self._futures[key] = future
        # hand the request's span context to the engine: the tick that
        # processes this row parents itself on it (tracing registry)
        tracing.register_pending(key, tracing.current_context())
        try:
            self._session.insert(key, vals)
            result = await future
        finally:
            tracing.unregister_pending(key)
        if self._delete_completed:
            self._session.remove(key, vals)
        return web.json_response(result)

    def _deadline_for(self, request: web.Request) -> float:
        """Absolute monotonic deadline: the ``x-pathway-deadline-ms``
        budget header (clamped to the configured cap), or the endpoint
        default when absent/garbled."""
        import math

        cfg = self._qos
        budget_ms = None
        raw = request.headers.get("x-pathway-deadline-ms")
        if raw is not None:
            try:
                budget_ms = float(raw)
            except ValueError:
                budget_ms = None
            # nan/inf would bypass the clamp AND both sides of the
            # batcher's live/dead partition — treat as absent
            if budget_ms is not None and not math.isfinite(budget_ms):
                budget_ms = None
        if budget_ms is None:
            budget_ms = cfg.default_deadline_ms
        budget_ms = min(budget_ms, cfg.max_deadline_ms)
        return _time.monotonic() + budget_ms / 1000.0

    async def _handle_gated(
        self,
        request: web.Request,
        key: int,
        vals: tuple,
        values: dict | None = None,
    ) -> web.Response:
        """Surge Gate serving path: admission → EDF queue → micro-batch
        dispatch → engine tick → response, with explicit shedding.

        Phoenix degradation: while the engine is recovering (peer
        failure / restore replay), reads are answered from the last
        hydrated index snapshot via the route's registered stale
        responder instead of queueing behind a tick loop that is not
        running — with explicit staleness headers and the
        ``x-pathway-max-staleness-ms`` bound honored."""
        from pathway_tpu.observability import tracing
        from pathway_tpu.serving import (
            DeadlineExceeded,
            PendingRequest,
            ShedError,
        )
        from pathway_tpu.serving import degrade

        reason = degrade.recovering()
        if reason is not None:
            return await self._handle_stale(
                request, values if values is not None else {}, reason
            )
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        dispatched: asyncio.Future = loop.create_future()
        deadline = self._deadline_for(request)
        req = PendingRequest(
            key,
            vals,
            deadline,
            loop=loop,
            dispatched=dispatched,
            # Tenant Weave identity: consumed only when the gate's
            # ledger is armed (PATHWAY_TENANT_QOS=1); inert otherwise
            tenant=request.headers.get("x-pathway-tenant"),
            tenant_class=request.headers.get("x-pathway-tenant-class"),
        )
        with self._futures_lock:
            self._futures[key] = future
        tracing.register_pending(key, tracing.current_context())
        admitted = False
        timed_out = False
        try:
            try:
                self._gate.submit(req)
                admitted = True
            except ShedError as e:
                return web.json_response(
                    {"error": f"request shed: {e.reason}"},
                    status=e.status,
                    headers={"Retry-After": f"{e.retry_after_s:.3f}"},
                )
            except DeadlineExceeded:
                return web.json_response(
                    {"error": "deadline exceeded"}, status=504
                )
            try:
                # queue.wait: admission to micro-batch release — the
                # QoS-added latency, as a child of the request span
                with tracing.get_tracer().span(
                    "queue.wait", route=self._route
                ) as qs:
                    batch_size = await dispatched
                    qs.set_attribute("batch", batch_size)
            except DeadlineExceeded:
                # dropped at flush: the engine never saw this request
                return web.json_response(
                    {"error": "deadline exceeded before dispatch"},
                    status=504,
                )
            except ShedError as e:
                return web.json_response(
                    {"error": f"request shed: {e.reason}"},
                    status=e.status,
                    headers={"Retry-After": f"{e.retry_after_s:.3f}"},
                )
            try:
                result = await asyncio.wait_for(
                    future, timeout=max(0.001, deadline - _time.monotonic())
                )
            except asyncio.TimeoutError:
                # dispatched but the result missed the deadline; KEEP
                # the registry entry so the tick that eventually reaches
                # this row skips its device work (index_node) — _deliver
                # or the registry's lazy sweep cleans it up
                timed_out = True
                return web.json_response(
                    {"error": "deadline exceeded"}, status=504
                )
        finally:
            tracing.unregister_pending(key)
            with self._futures_lock:
                self._futures.pop(key, None)
            if admitted:
                # settle the race with the batcher atomically: a
                # handler cancelled (client disconnect) while its
                # request is still queued abandons it, so the flush
                # skips the row — it must not claim an engine batch
                # slot or a dispatch-window slot nobody will ever free
                was_dispatched = not req.abandon()
                self._gate.complete(
                    None if timed_out else key,
                    was_dispatched=was_dispatched,
                )
                if was_dispatched and self._delete_completed:
                    try:
                        self._session.remove(key, vals)
                    except Exception:
                        pass
        return web.json_response(result)

    async def _handle_stale(
        self, request: web.Request, values: dict, reason: str
    ) -> web.Response:
        """Answer a read from the last hydrated snapshot while the
        engine recovers. No responder registered → explicit 503 (never
        hang a request on a tick loop that is not ticking)."""
        from pathway_tpu.serving import degrade

        staleness = degrade.staleness_seconds()
        stale_hdrs = {
            "x-pathway-stale": "true",
            "x-pathway-staleness-seconds": (
                f"{staleness:.3f}" if staleness is not None else "unknown"
            ),
        }
        responder = degrade.stale_responder(self._route)
        if responder is None:
            degrade.count_degraded_shed(self._route, "no_responder")
            return web.json_response(
                {"error": f"engine recovering: {reason}"},
                status=503,
                headers={"Retry-After": "1.0", **stale_hdrs},
            )
        max_raw = request.headers.get("x-pathway-max-staleness-ms")
        if max_raw is not None:
            import math

            try:
                bound_ms = float(max_raw)
            except ValueError:
                bound_ms = None
            if bound_ms is not None and math.isfinite(bound_ms):
                if staleness is None or staleness * 1000.0 > bound_ms:
                    degrade.count_degraded_shed(
                        self._route, "max_staleness"
                    )
                    return web.json_response(
                        {
                            "error": "snapshot staler than "
                            "x-pathway-max-staleness-ms while the "
                            f"engine recovers: {reason}"
                        },
                        status=503,
                        headers={"Retry-After": "1.0", **stale_hdrs},
                    )
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(None, responder, values)
        except Exception:
            degrade.count_degraded_shed(self._route, "responder_error")
            return web.json_response(
                {"error": f"stale read failed while recovering: {reason}"},
                status=503,
                headers={"Retry-After": "1.0", **stale_hdrs},
            )
        degrade.count_stale_served(self._route)
        return web.json_response(result, headers=stale_hdrs)

    def _deliver(self, key: int, payload: Any) -> None:
        if self._gate is not None:
            # late result for a 504'd request: its deadline entry was
            # deliberately left registered so the engine could skip the
            # work — this is the natural cleanup point
            from pathway_tpu.serving import deadline as _sdl

            _sdl.unregister(key)
        with self._futures_lock:
            future = self._futures.pop(key, None)
        if future is None:
            return
        loop = future.get_loop()
        loop.call_soon_threadsafe(
            lambda: future.done() or future.set_result(payload)
        )

    def _key_for(self, values):  # keys are assigned in _handle
        raise NotImplementedError


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: SchemaMetaclass | None = None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int | None = 50,
    keep_queries: bool | None = None,
    delete_completed_queries: bool | None = None,
    request_validator: Any = None,
    documentation: EndpointDocumentation | None = None,
    qos: Any = None,
) -> tuple[Table, Any]:
    """Returns (queries_table, response_writer). Call
    ``response_writer(result_table)`` where result_table has columns
    ``query_id`` (Pointer) and ``result`` (reference: _server.py:624).

    ``qos``: a :class:`pathway_tpu.serving.QoSConfig` puts the endpoint
    behind a Surge Gate (micro-batching + deadline-aware admission
    control + graceful overload). ``None`` keeps the ungated per-request
    path unless ``PATHWAY_SERVING_ENABLED=1``, in which case the
    env-configured gate applies."""
    if delete_completed_queries is None:
        delete_completed_queries = not bool(keep_queries)
    if qos is None:
        from pathway_tpu.serving import QoSConfig, serving_enabled_via_env

        if serving_enabled_via_env():
            qos = QoSConfig.from_env()
    if webserver is None:
        assert host is not None and port is not None
        webserver = PathwayWebserver(host, port)
    if schema is None:
        schema = schema_from_types(query=str)
        fmt = "raw"
    else:
        fmt = "custom"
    subject = RestServerSubject(
        webserver,
        route,
        schema,
        methods,
        delete_completed_queries,
        format=fmt,
        documentation=documentation,
        qos=qos,
    )
    queries = python_read(subject, schema=schema)

    def response_writer(response_table: Table) -> None:
        col_names = response_table.column_names()
        assert "query_id" in col_names and "result" in col_names, (
            "response table must have query_id and result columns"
        )
        qi = col_names.index("query_id")
        ri = col_names.index("result")

        def on_batch(t: int, batch: DiffBatch) -> None:
            for k, d, vals in batch.iter_rows():
                if d <= 0:
                    continue
                qid = vals[qi]
                result = vals[ri]
                if isinstance(result, Json):
                    result = result.value
                subject._deliver(int(qid), _jsonable(result))

        node = OutputNode(response_table._node, on_batch)
        parse_graph.G.add_output(node)

    return queries, response_writer


def _jsonable(v: Any):
    import numpy as np

    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, Pointer):
        return str(v)
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v
