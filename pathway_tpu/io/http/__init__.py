"""pw.io.http — REST ingress/egress
(reference: python/pathway/io/http — rest_connector:624, PathwayWebserver:329,
RestServerSubject:490; aiohttp-based)."""

from pathway_tpu.io.http._server import (
    EndpointDocumentation,
    EndpointExamples,
    PathwayWebserver,
    rest_connector,
)
from pathway_tpu.io.http._client import read, write

__all__ = [
    "PathwayWebserver",
    "rest_connector",
    "read",
    "write",
    "EndpointDocumentation",
    "EndpointExamples",
]
