"""Filesystem connector (reference: python/pathway/io/fs + Rust posix-like
scanner, src/connectors/scanner/filesystem.rs:146). Static mode reads once;
streaming mode polls the path for new/changed files and feeds diffs."""

from __future__ import annotations

import csv as _csv
import glob
import json as _json
import os
import threading
import time
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import ref_scalar, sequential_key
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


def _list_files(path: str, with_metadata_glob: str | None = None) -> list[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return out
    if any(ch in path for ch in "*?["):
        return sorted(glob.glob(path))
    if os.path.exists(path):
        return [path]
    return []


def _parse_file(
    fpath: str,
    format: str,
    schema,
    csv_settings=None,
    with_metadata: bool = False,
) -> Iterable[tuple]:
    """Yield (pk_values, values_tuple) rows."""
    if format in ("plaintext", "plaintext_by_file"):
        if format == "plaintext_by_file":
            with open(fpath, "r", errors="replace") as f:
                yield (fpath,), (f.read(),)
        else:
            with open(fpath, "r", errors="replace") as f:
                for i, line in enumerate(f):
                    line = line.rstrip("\n")
                    yield (fpath, i), (line,)
        return
    if format == "binary":
        with open(fpath, "rb") as f:
            yield (fpath,), (f.read(),)
        return
    col_names = list(schema.column_names()) if schema else None
    if format == "csv":
        delim = ","
        if csv_settings is not None:
            delim = getattr(csv_settings, "delimiter", ",")
        with open(fpath, newline="") as f:
            reader = _csv.DictReader(f, delimiter=delim)
            for i, row in enumerate(reader):
                names = col_names or list(row.keys())
                vals = tuple(
                    _coerce(row.get(n), schema, n) for n in names
                )
                yield (fpath, i), vals
        return
    if format in ("json", "jsonlines"):
        with open(fpath, "r") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                obj = _json.loads(line)
                names = col_names or list(obj.keys())
                vals = tuple(_coerce_json(obj.get(n), schema, n) for n in names)
                yield (fpath, i), vals
        return
    raise ValueError(f"unknown format {format!r}")


def _coerce(v: Any, schema, name: str) -> Any:
    if v is None:
        return None
    if schema is None:
        return v
    d = schema.dtypes().get(name, dt.ANY).strip_optional()
    try:
        if d == dt.INT:
            return int(v)
        if d == dt.FLOAT:
            return float(v)
        if d == dt.BOOL:
            return v if isinstance(v, bool) else v.lower() in ("true", "1")
        if d == dt.STR:
            return str(v)
        if d == dt.JSON:
            return Json(_json.loads(v) if isinstance(v, str) else v)
    except (ValueError, TypeError):
        return None
    return v


def _coerce_json(v: Any, schema, name: str) -> Any:
    if schema is None:
        return v
    d = schema.dtypes().get(name, dt.ANY).strip_optional()
    if d == dt.JSON:
        return Json(v)
    if d == dt.FLOAT and isinstance(v, int):
        return float(v)
    if isinstance(v, (list, dict)) and d not in (dt.JSON,):
        return Json(v)
    return v


class _FsStaticSource(StaticSource):
    def __init__(self, path, format, schema, column_names, csv_settings, pk_cols):
        super().__init__(column_names)
        self.path = path
        self.format = format
        self.schema = schema
        self.csv_settings = csv_settings
        self.pk_cols = pk_cols

    def events(self):
        rows = []
        counter = 0
        for fpath in _list_files(self.path):
            for pk, vals in _parse_file(
                fpath, self.format, self.schema, self.csv_settings
            ):
                if self.pk_cols:
                    key = int(
                        ref_scalar(
                            *[
                                vals[self.column_names.index(c)]
                                for c in self.pk_cols
                            ]
                        )
                    )
                else:
                    key = int(ref_scalar(*pk))
                rows.append((key, 1, vals))
                counter += 1
        if rows:
            yield 0, DiffBatch.from_rows(rows, self.column_names)


class _FsStreamingSource(StreamingSource):
    def __init__(
        self,
        path,
        format,
        schema,
        column_names,
        csv_settings,
        pk_cols,
        refresh_s: float = 0.2,
        with_deletions: bool = True,
    ):
        super().__init__(column_names)
        self.path = path
        self.format = format
        self.schema = schema
        self.csv_settings = csv_settings
        self.pk_cols = pk_cols
        self.refresh_s = refresh_s
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._seen: dict[str, tuple[float, int]] = {}  # path -> (mtime, size)
        self._emitted: dict[str, list] = {}  # path -> [(key, vals)]
        self.persistent_id: str | None = None

    # --- persistence hooks (reference: Reader::seek + OffsetValue,
    # src/connectors/data_storage.rs:402, src/connectors/offset.rs) -----------

    def offset_state(self) -> dict:
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _scan_once(self):
        for fpath in _list_files(self.path):
            try:
                st = os.stat(fpath)
            except OSError:
                continue
            sig = (st.st_mtime, st.st_size)
            if self._seen.get(fpath) == sig:
                continue
            # build the whole file's diff (retraction of the previous
            # version + new rows), then enqueue it atomically together with
            # the offset snapshot that covers it — a persistence commit can
            # then never record this file as seen without its rows being in
            # the drained (and thus logged) stream
            rows: list[tuple[int, int, tuple]] = [
                (key, -1, vals) for key, vals in self._emitted.get(fpath, [])
            ]
            emitted = []
            try:
                for pk, vals in _parse_file(
                    fpath, self.format, self.schema, self.csv_settings
                ):
                    if self.pk_cols:
                        key = int(
                            ref_scalar(
                                *[
                                    vals[self.column_names.index(c)]
                                    for c in self.pk_cols
                                ]
                            )
                        )
                    else:
                        key = int(ref_scalar(*pk))
                    rows.append((key, 1, vals))
                    emitted.append((key, vals))
            except OSError:
                continue
            self._seen[fpath] = sig
            self._emitted[fpath] = emitted
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan_once()
            self._stop.wait(self.refresh_s)


def read(
    path: str,
    *,
    format: str = "csv",
    schema: Any = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: Any = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format in ("plaintext", "plaintext_by_file"):
        column_names = ["data"]
        dtypes = {"data": dt.STR}
        schema_ = None
    elif format == "binary":
        column_names = ["data"]
        dtypes = {"data": dt.BYTES}
        schema_ = None
    else:
        assert schema is not None, f"schema required for format {format!r}"
        column_names = list(schema.column_names())
        dtypes = dict(schema.dtypes())
        schema_ = schema
    pk_cols = schema_.primary_key_columns() if schema_ else None
    if mode in ("static",):
        source: Any = _FsStaticSource(
            path, format, schema_, column_names, csv_settings, pk_cols
        )
    else:
        source = _FsStreamingSource(
            path, format, schema_, column_names, csv_settings, pk_cols
        )
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dtypes, Universe())


class _FileWriter:
    def __init__(self, filename: str, format: str, column_names: Sequence[str]):
        self.filename = filename
        self.format = format
        self.column_names = list(column_names)
        self._file = open(filename, "w", newline="")
        if format == "csv":
            self._writer = _csv.writer(self._file)
            self._writer.writerow(list(column_names) + ["time", "diff"])

    def on_batch(self, t: int, batch: DiffBatch) -> None:
        for k, d, vals in batch.iter_rows():
            if self.format == "csv":
                self._writer.writerow(list(vals) + [t, d])
            else:
                obj = dict(zip(self.column_names, [_jsonable(v) for v in vals]))
                obj["time"] = t
                obj["diff"] = d
                self._file.write(_json.dumps(obj) + "\n")
        self._file.flush()

    def close(self) -> None:
        self._file.close()


def _jsonable(v: Any) -> Any:
    from pathway_tpu.io._utils import jsonable

    return jsonable(v)


def write(table: Table, filename: str, *, format: str = "json", **kwargs) -> None:
    from pathway_tpu.engine.nodes import OutputNode

    writer = _FileWriter(filename, format, table.column_names())
    node = OutputNode(table._node, writer.on_batch, writer.close)
    parse_graph.G.add_output(node)
