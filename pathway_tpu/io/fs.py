"""Filesystem connector (reference: python/pathway/io/fs + Rust posix-like
scanner, src/connectors/scanner/filesystem.rs:146). Static mode reads once;
streaming mode polls the path for new/changed files and feeds diffs."""

from __future__ import annotations

import csv as _csv
import glob
import json as _json
import os
import threading
import time
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.batch import DiffBatch, make_column
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource, StreamingSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.api import ref_scalar, sequential_key
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


def _list_files(path: str, with_metadata_glob: str | None = None) -> list[str]:
    path = os.fspath(path)
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return out
    if any(ch in path for ch in "*?["):
        return sorted(glob.glob(path))
    if os.path.exists(path):
        return [path]
    return []


def _parse_file(
    fpath: str,
    format: str,
    schema,
    csv_settings=None,
    with_metadata: bool = False,
) -> Iterable[tuple]:
    """Yield (pk_values, values_tuple) rows."""
    if format in ("plaintext", "plaintext_by_file"):
        if format == "plaintext_by_file":
            with open(fpath, "r", errors="replace") as f:
                yield (fpath,), (f.read(),)
        else:
            with open(fpath, "r", errors="replace") as f:
                for i, line in enumerate(f):
                    line = line.rstrip("\n")
                    yield (fpath, i), (line,)
        return
    if format == "binary":
        with open(fpath, "rb") as f:
            yield (fpath,), (f.read(),)
        return
    col_names = list(schema.column_names()) if schema else None
    if format == "csv":
        delim = ","
        if csv_settings is not None:
            delim = getattr(csv_settings, "delimiter", ",")
        coercers = _make_coercers(schema, col_names, _coerce_one)
        with open(fpath, newline="") as f:
            reader = _csv.DictReader(f, delimiter=delim)
            for i, row in enumerate(reader):
                if coercers is not None:
                    vals = tuple(fn(row.get(n)) for n, fn in coercers)
                else:
                    vals = tuple(row.values())
                yield (fpath, i), vals
        return
    if format in ("json", "jsonlines"):
        coercers = _make_coercers(schema, col_names, _coerce_json_one)
        with open(fpath, "r") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                obj = _json.loads(line)
                if coercers is not None:
                    vals = tuple(fn(obj.get(n)) for n, fn in coercers)
                else:
                    names = col_names or list(obj.keys())
                    vals = tuple(obj.get(n) for n in names)
                yield (fpath, i), vals
        return
    raise ValueError(f"unknown format {format!r}")


def _make_coercers(schema, col_names, make_one):
    """Per-column coercion closures resolved ONCE per file — dtype lookup
    and comparison per row was the parse hot spot."""
    if schema is None or col_names is None:
        return None
    dtypes = schema.dtypes()
    return [
        (n, make_one(dtypes.get(n, dt.ANY).strip_optional())) for n in col_names
    ]


def _coerce_one(d):
    """Column coercer for text (csv) input values."""
    if d == dt.INT:
        return lambda v: None if v is None else _safe(int, v)
    if d == dt.FLOAT:
        return lambda v: None if v is None else _safe(float, v)
    if d == dt.BOOL:
        return lambda v: (
            None
            if v is None
            else (v if isinstance(v, bool) else v.lower() in ("true", "1"))
        )
    if d == dt.STR:
        return lambda v: None if v is None else str(v)
    if d == dt.JSON:
        return lambda v: (
            None
            if v is None
            else _safe(lambda x: Json(_json.loads(x) if isinstance(x, str) else x), v)
        )
    return lambda v: v


def _safe(fn, v):
    try:
        return fn(v)
    except (ValueError, TypeError):
        return None


def _coerce_json_one(d):
    """Column coercer for already-typed (json) input values. Non-JSON
    dtypes wrap stray list/dict values into Json (matching the historical
    fs behavior the s3 scanner shares). Datetime/duration columns parse
    the Json serde format back (nanosecond ISO strings / ns ints)."""
    from pathway_tpu.internals.datetime_types import (
        DateTimeNaive,
        DateTimeUtc,
        Duration,
    )

    if d == dt.JSON:
        return lambda v: v if isinstance(v, Json) else Json(v)
    if d == dt.DATE_TIME_NAIVE:
        return lambda v: (
            DateTimeNaive(v) if isinstance(v, str) else v
        )
    if d == dt.DATE_TIME_UTC:
        return lambda v: DateTimeUtc(v) if isinstance(v, str) else v
    if d == dt.DURATION:
        return lambda v: (
            Duration(nanoseconds=v)
            if isinstance(v, int) and not isinstance(v, bool)
            else v
        )
    if d == dt.FLOAT:

        def as_float(v):
            if isinstance(v, int):
                return float(v)
            if isinstance(v, (list, dict)):
                return Json(v)
            return v

        return as_float

    def generic(v):
        if isinstance(v, (list, dict)):
            return Json(v)
        return v

    return generic


def _coerce(v: Any, schema, name: str) -> Any:
    """Single-value text coercion (same rules as the per-column closures —
    kept for callers that coerce ad hoc, e.g. the s3 scanner)."""
    if schema is None:
        return v
    return _coerce_one(schema.dtypes().get(name, dt.ANY).strip_optional())(v)


def _coerce_json(v: Any, schema, name: str) -> Any:
    if schema is None:
        return v
    return _coerce_json_one(
        schema.dtypes().get(name, dt.ANY).strip_optional()
    )(v)


class _FsStaticSource(StaticSource):
    def __init__(self, path, format, schema, column_names, csv_settings, pk_cols):
        super().__init__(column_names)
        self.path = path
        self.format = format
        self.schema = schema
        self.csv_settings = csv_settings
        self.pk_cols = pk_cols

    def events(self):
        import numpy as np

        from pathway_tpu.internals.api import ref_scalars_columns

        all_vals: list[tuple] = []
        all_pks: list[tuple] = []
        for fpath in _list_files(self.path):
            for pk, vals in _parse_file(
                fpath, self.format, self.schema, self.csv_settings
            ):
                all_vals.append(vals)
                all_pks.append(pk)
        if not all_vals:
            return
        n = len(all_vals)
        # batch key derivation through the native hasher — one call for the
        # whole snapshot instead of a per-row ref_scalar
        if self.pk_cols:
            pk_idx = [self.column_names.index(c) for c in self.pk_cols]
            key_cols = [[v[i] for v in all_vals] for i in pk_idx]
        else:
            width = len(all_pks[0])
            key_cols = [[p[i] for p in all_pks] for i in range(width)]
        keys = ref_scalars_columns(key_cols, n)
        cols = {
            name: make_column([v[i] for v in all_vals])
            for i, name in enumerate(self.column_names)
        }
        yield 0, DiffBatch(keys, np.ones(n, dtype=np.int64), cols)


class _FsStreamingSource(StreamingSource):
    def __init__(
        self,
        path,
        format,
        schema,
        column_names,
        csv_settings,
        pk_cols,
        refresh_s: float = 0.2,
        with_deletions: bool = True,
    ):
        super().__init__(column_names)
        self.path = path
        self.format = format
        self.schema = schema
        self.csv_settings = csv_settings
        self.pk_cols = pk_cols
        self.refresh_s = refresh_s
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._seen: dict[str, tuple[float, int]] = {}  # path -> (mtime, size)
        self._emitted: dict[str, list] = {}  # path -> [(key, vals)]
        self.persistent_id: str | None = None

    # --- persistence hooks (reference: Reader::seek + OffsetValue,
    # src/connectors/data_storage.rs:402, src/connectors/offset.rs) -----------

    def offset_state(self) -> dict:
        return {"seen": dict(self._seen), "emitted": dict(self._emitted)}

    def seek(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))
        self._emitted = dict(state.get("emitted", {}))

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _scan_once(self):
        for fpath in _list_files(self.path):
            try:
                st = os.stat(fpath)
            except OSError:
                continue
            sig = (st.st_mtime, st.st_size)
            if self._seen.get(fpath) == sig:
                continue
            # build the whole file's diff (retraction of the previous
            # version + new rows), then enqueue it atomically together with
            # the offset snapshot that covers it — a persistence commit can
            # then never record this file as seen without its rows being in
            # the drained (and thus logged) stream
            rows: list[tuple[int, int, tuple]] = [
                (key, -1, vals) for key, vals in self._emitted.get(fpath, [])
            ]
            emitted = []
            try:
                for pk, vals in _parse_file(
                    fpath, self.format, self.schema, self.csv_settings
                ):
                    if self.pk_cols:
                        key = int(
                            ref_scalar(
                                *[
                                    vals[self.column_names.index(c)]
                                    for c in self.pk_cols
                                ]
                            )
                        )
                    else:
                        key = int(ref_scalar(*pk))
                    rows.append((key, 1, vals))
                    emitted.append((key, vals))
            except OSError:
                continue
            self._seen[fpath] = sig
            self._emitted[fpath] = emitted
            self.session.insert_batch(rows, self.offset_state())

    def _loop(self):
        while not self._stop.is_set():
            self._scan_once()
            self._stop.wait(self.refresh_s)


def read(
    path: str,
    *,
    format: str = "csv",
    schema: Any = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: Any = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format in ("plaintext", "plaintext_by_file"):
        column_names = ["data"]
        dtypes = {"data": dt.STR}
        schema_ = None
    elif format == "binary":
        column_names = ["data"]
        dtypes = {"data": dt.BYTES}
        schema_ = None
    else:
        assert schema is not None, f"schema required for format {format!r}"
        column_names = list(schema.column_names())
        dtypes = dict(schema.dtypes())
        schema_ = schema
    pk_cols = schema_.primary_key_columns() if schema_ else None
    if mode in ("static",):
        source: Any = _FsStaticSource(
            path, format, schema_, column_names, csv_settings, pk_cols
        )
    else:
        source = _FsStreamingSource(
            path, format, schema_, column_names, csv_settings, pk_cols
        )
    source.persistent_id = persistent_id or name
    node = InputNode(source, column_names)
    return Table._from_node(node, dtypes, Universe())


class _FileWriter:
    def __init__(self, filename: str, format: str, column_names: Sequence[str]):
        self.filename = filename
        self.format = format
        self.column_names = list(column_names)
        self._file = open(filename, "w", newline="")
        if format == "csv":
            self._writer = _csv.writer(self._file)
            self._writer.writerow(list(column_names) + ["time", "diff"])

    def on_batch(self, t: int, batch: DiffBatch) -> None:
        for k, d, vals in batch.iter_rows():
            if self.format == "csv":
                self._writer.writerow(list(vals) + [t, d])
            else:
                obj = dict(zip(self.column_names, [_jsonable(v) for v in vals]))
                obj["time"] = t
                obj["diff"] = d
                # Json.dumps: datetimes as nanosecond ISO strings, durations
                # as nanosecond ints (reference JsonLinesFormatter serde)
                self._file.write(Json.dumps(obj) + "\n")
        self._file.flush()

    def close(self) -> None:
        self._file.close()


def _jsonable(v: Any) -> Any:
    from pathway_tpu.io._utils import jsonable

    return jsonable(v)


def write(table: Table, filename: str, *, format: str = "json", **kwargs) -> None:
    from pathway_tpu.engine.nodes import OutputNode

    writer = _FileWriter(filename, format, table.column_names())
    node = OutputNode(table._node, writer.on_batch, writer.close)
    parse_graph.G.add_output(node)
