"""pw.statistical (reference: stdlib/statistical/_interpolate.py)."""

from __future__ import annotations

from enum import Enum
from typing import Any


class InterpolateMode(Enum):
    LINEAR = "linear"


def interpolate(
    table,
    timestamp: Any,
    *values: Any,
    mode: InterpolateMode = InterpolateMode.LINEAR,
):
    """Linear interpolation of missing (None) values along a time ordering
    (reference: stdlib/statistical/_interpolate.py). Each null cell is
    filled between the NEAREST NON-NULL neighbors of its own column in
    timestamp order (nulls between them are skipped over); leading/trailing
    gaps clamp to the first/last known value."""
    import bisect

    from pathway_tpu.stdlib.utils.col import multiapply_all_rows

    assert mode == InterpolateMode.LINEAR
    names = [v.name for v in values]

    def _missing(v):
        # the all-rows bridge goes through pandas, which stores missing
        # optional floats as NaN
        return v is None or (isinstance(v, float) and v != v)

    def fn(ts_col, *val_cols):
        outs = []
        for vc in val_cols:
            pts = sorted(
                (ts_col[i], vc[i])
                for i in range(len(vc))
                if not _missing(vc[i])
            )
            xs = [p[0] for p in pts]
            res = []
            for i in range(len(vc)):
                if not _missing(vc[i]):
                    res.append(vc[i])
                    continue
                t0 = ts_col[i]
                j = bisect.bisect_left(xs, t0)
                left = pts[j - 1] if j > 0 else None
                right = pts[j] if j < len(pts) else None
                if left is None and right is None:
                    res.append(None)
                elif left is None:
                    res.append(float(right[1]))
                elif right is None:
                    res.append(float(left[1]))
                else:
                    w = (t0 - left[0]) / (right[0] - left[0])
                    res.append(left[1] + w * (right[1] - left[1]))
            outs.append(res)
        return outs

    interped = multiapply_all_rows(
        timestamp, *values, fun=fn, result_col_names=names
    )
    # full table returned in the ORIGINAL column order, interpolated
    # columns substituted in place (reference: interpolate returns the
    # full table)
    return table.select(
        **{
            n: (interped[n] if n in names else table[n])
            for n in table.column_names()
        }
    )


__all__ = ["interpolate", "InterpolateMode"]
