"""pw.statistical (reference: stdlib/statistical/_interpolate.py)."""

from __future__ import annotations

from enum import Enum
from typing import Any


class InterpolateMode(Enum):
    LINEAR = "linear"


def interpolate(
    table,
    timestamp: Any,
    *values: Any,
    mode: InterpolateMode = InterpolateMode.LINEAR,
):
    """Linear interpolation of missing (None) values along a time ordering
    (reference: stdlib/statistical/_interpolate.py)."""
    import pathway_tpu as pw

    sorted_ptrs = table.sort(key=timestamp)
    t = table.with_columns(
        _prev=sorted_ptrs.prev, _next=sorted_ptrs.next, _ts=timestamp
    )

    out = {}
    for v in values:
        name = v.name

        @pw.udf
        def interp(val, ts, prev_val, prev_ts, next_val, next_ts):
            if val is not None:
                return val
            if prev_val is None and next_val is None:
                return None
            if prev_val is None:
                return next_val
            if next_val is None:
                return prev_val
            if next_ts == prev_ts:
                return prev_val
            w = (ts - prev_ts) / (next_ts - prev_ts)
            return prev_val + w * (next_val - prev_val)

        prev_rows = table.ix(t._prev, optional=True)
        next_rows = table.ix(t._next, optional=True)
        prev_t = t.ix(t._prev, optional=True)
        next_t = t.ix(t._next, optional=True)
        out[name] = interp(
            t[name],
            t._ts,
            prev_rows[name],
            prev_t._ts,
            next_rows[name],
            next_t._ts,
        )
    return table.select(**out)


__all__ = ["interpolate", "InterpolateMode"]
