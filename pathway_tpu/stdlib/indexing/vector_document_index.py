"""Default vector document index factories
(reference: stdlib/indexing/vector_document_index.py:12-157)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    LshKnn,
    TpuKnn,
    USearchKnn,
    USearchMetricKind,
)


def default_vector_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    return default_usearch_knn_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = USearchKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        metric=USearchMetricKind.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_brute_force_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = BruteForceKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_lsh_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = LshKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_ivf_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder: Any = None,
    dimensions: int | None = None,
    metadata_column: ColumnExpression | None = None,
    n_clusters: int | None = None,
    n_probe: int | None = None,
) -> DataIndex:
    """IVF document index — sub-linear queries for corpora past the
    HBM-resident brute-force tier (ops/ivf.py design note)."""
    from pathway_tpu.stdlib.indexing.nearest_neighbors import IvfKnn

    inner = IvfKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        n_clusters=n_clusters,
        n_probe=n_probe,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)
