"""HybridIndex — reciprocal-rank fusion over several inner indexes
(reference: stdlib/indexing/hybrid_index.py:14)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.colnames import _INDEX_REPLY
from pathway_tpu.stdlib.indexing.data_index import InnerIndex
from pathway_tpu.stdlib.indexing.retrievers import InnerIndexFactory


class HybridIndex(InnerIndex):
    def __init__(self, inner_indexes: Sequence[InnerIndex], k: float = 60.0):
        assert inner_indexes, "HybridIndex needs at least one inner index"
        first = inner_indexes[0]
        super().__init__(first.data_column, first.metadata_column)
        self.inner_indexes = list(inner_indexes)
        self.k = k

    def _fuse(self, reply_tables: list[Table]) -> Table:
        k = self.k

        def rrf(*replies) -> tuple:
            scores: dict = {}
            for reply in replies:
                if reply is None:
                    continue
                for rank, pair in enumerate(reply):
                    ptr = pair[0]
                    scores[ptr] = scores.get(ptr, 0.0) + 1.0 / (k + rank + 1)
            ranked = sorted(scores.items(), key=lambda kv: -kv[1])
            return tuple((ptr, s) for ptr, s in ranked)

        base = reply_tables[0]
        args = [t[_INDEX_REPLY] for t in reply_tables]
        return base.select(
            **{_INDEX_REPLY: apply_with_type(rrf, tuple, *args)}
        )

    def query(self, query_column, *, number_of_matches=3, metadata_filter=None):
        replies = [
            ix.query(
                query_column,
                number_of_matches=number_of_matches,
                metadata_filter=metadata_filter,
            )
            for ix in self.inner_indexes
        ]
        return self._fuse(replies)

    def query_as_of_now(
        self, query_column, *, number_of_matches=3, metadata_filter=None
    ):
        replies = [
            ix.query_as_of_now(
                query_column,
                number_of_matches=number_of_matches,
                metadata_filter=metadata_filter,
            )
            for ix in self.inner_indexes
        ]
        return self._fuse(replies)


@dataclass
class HybridIndexFactory(InnerIndexFactory):
    retriever_factories: list[Any]
    k: float = 60.0

    def build_inner_index(self, data_column, metadata_column=None):
        inner = [
            f.build_inner_index(data_column, metadata_column)
            for f in self.retriever_factories
        ]
        return HybridIndex(inner, k=self.k)
