"""KNN inner indexes (reference: stdlib/indexing/nearest_neighbors.py:
USearchKnn:65, BruteForceKnn:170, LshKnn:262 + factories:407).

On TPU every dense index is the same machine: an MXU matmul + top-k over a
device-resident corpus (exact — at ≤10M×384 this beats CPU-side approximate
HNSW, per TPU-KNN arXiv 2206.14286). `USearchKnn` / `BruteForceKnn` keep the
reference's parameter surfaces; both lower to `TpuDenseKnnIndex`. `LshKnn`
keeps candidate-bucketing semantics with projections computed on device."""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing._index_impls import (
    LshKnnIndex,
    TpuDenseKnnIndex,
)
from pathway_tpu.stdlib.indexing.data_index import EngineInnerIndex
from pathway_tpu.stdlib.indexing.retrievers import InnerIndexFactory


class USearchMetricKind(Enum):
    COS = "cosine"
    IP = "dot"
    L2SQ = "l2sq"


class BruteForceKnnMetricKind(Enum):
    COS = "cosine"
    IP = "dot"
    L2SQ = "l2sq"


class DistanceTypes(Enum):
    EUCLIDEAN = "euclidean"
    COSINE = "cosine"


def _metric_name(metric: Any, default: str = "cosine") -> str:
    if metric is None:
        return default
    if isinstance(metric, (USearchMetricKind, BruteForceKnnMetricKind)):
        return metric.value
    return str(metric)


class TpuKnn(EngineInnerIndex):
    """Exact dense KNN on TPU; corpus optionally sharded over a mesh axis."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        metric: Any = None,
        embedder: Any = None,
        mesh: Any = None,
        axis: str = "data",
    ):
        metric_s = _metric_name(metric)
        super().__init__(
            data_column,
            metadata_column,
            index_factory=lambda: TpuDenseKnnIndex(
                dimensions=dimensions,
                metric=metric_s,
                reserved_space=reserved_space,
                mesh=mesh,
                axis=axis,
            ),
            embedder=embedder,
        )
        self.dimensions = dimensions
        self.reserved_space = reserved_space
        self.metric = metric_s


class BruteForceKnn(TpuKnn):
    """Reference-parity class (stdlib/indexing/nearest_neighbors.py:170);
    identical TPU execution."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        auxiliary_space: int = 512,
        metric: Any = None,
        embedder: Any = None,
        **kwargs: Any,
    ):
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            embedder=embedder,
            **kwargs,
        )


class USearchKnn(TpuKnn):
    """Reference-parity class (stdlib/indexing/nearest_neighbors.py:65).
    USearch's HNSW knobs are accepted for API compatibility; retrieval is
    exact on TPU (recall 1.0 ≥ any HNSW setting)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        dimensions: int | None = None,
        reserved_space: int = 1024,
        metric: Any = None,
        connectivity: int = 0,
        expansion_add: int = 0,
        expansion_search: int = 0,
        embedder: Any = None,
        **kwargs: Any,
    ):
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            embedder=embedder,
            **kwargs,
        )


class LshKnn(EngineInnerIndex):
    """LSH-bucketed approximate KNN
    (reference: stdlib/indexing/nearest_neighbors.py:262)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        embedder: Any = None,
    ):
        metric = "cosine" if str(distance_type) == "cosine" else "l2sq"
        super().__init__(
            data_column,
            metadata_column,
            index_factory=lambda: LshKnnIndex(
                dimensions=dimensions,
                n_or=n_or,
                n_and=n_and,
                bucket_length=bucket_length,
                metric=metric,
            ),
            embedder=embedder,
        )


# --- factories (reference: nearest_neighbors.py:407+) -----------------------


@dataclass(kw_only=True)
class TpuKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    metric: Any = None
    embedder: Any = None
    mesh: Any = None

    def build_inner_index(self, data_column, metadata_column=None):
        return TpuKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
            mesh=self.mesh,
        )


def _probe_dimensions(embedder) -> int:
    """Dimensionality of an embedder (reference factories defer dimensions
    to the embedder). API embedders expose get_embedding_dimension()
    (which handles async _embed); plain functions/UDF wrappers are invoked
    on a sample input, awaiting coroutines."""
    getter = getattr(embedder, "get_embedding_dimension", None)
    if callable(getter):
        return int(getter())
    fn = getattr(embedder, "__wrapped__", embedder)
    out = fn(".")
    if hasattr(out, "__await__"):
        import asyncio

        out = asyncio.run(out)
    return len(out)


def _check_factory_args(dimensions, embedder) -> None:
    # reference rule: embedder-backed indexes can probe their own output
    # dimension; without one, dimensions must be given explicitly
    if dimensions is None and embedder is None:
        raise ValueError(
            "Either `dimensions` or `embedder` must be provided to index "
            "factory."
        )


@dataclass(kw_only=True)
class BruteForceKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    auxiliary_space: int = 512
    metric: Any = None
    embedder: Any = None

    def __post_init__(self):
        _check_factory_args(self.dimensions, self.embedder)

    def build_inner_index(self, data_column, metadata_column=None):
        return BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
        )


@dataclass(kw_only=True)
class UsearchKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    metric: Any = None
    connectivity: int = 0
    expansion_add: int = 0
    expansion_search: int = 0
    embedder: Any = None

    def __post_init__(self):
        _check_factory_args(self.dimensions, self.embedder)

    def build_inner_index(self, data_column, metadata_column=None):
        return USearchKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
        )


@dataclass(kw_only=True)
class LshKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    n_or: int = 20
    n_and: int = 10
    bucket_length: float = 10.0
    distance_type: str = "euclidean"
    embedder: Any = None

    def __post_init__(self):
        _check_factory_args(self.dimensions, self.embedder)

    def build_inner_index(self, data_column, metadata_column=None):
        if self.dimensions is None:
            # LSH needs projection dimensionality up front; probe the
            # embedder (dense indexes infer it lazily instead)
            self.dimensions = _probe_dimensions(self.embedder)
        return LshKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
            distance_type=self.distance_type,
            embedder=self.embedder,
        )


class IvfKnn(EngineInnerIndex):
    """Two-level IVF index: MXU coarse quantization + exact fine scoring —
    the sub-linear / >HBM tier (design note: ops/ivf.py; reference
    counterpart: usearch HNSW, src/external_integration/
    usearch_integration.rs:20)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        dimensions: int | None = None,
        metric: Any = None,
        n_clusters: int | None = None,
        n_probe: int | None = None,
        min_train: int = 4096,
        embedder: Any = None,
    ):
        from pathway_tpu.stdlib.indexing._index_impls import IvfKnnIndex

        metric_s = _metric_name(metric)
        super().__init__(
            data_column,
            metadata_column,
            index_factory=lambda: IvfKnnIndex(
                dimensions=dimensions,
                metric=metric_s,
                n_clusters=n_clusters,
                n_probe=n_probe,
                min_train=min_train,
            ),
            embedder=embedder,
        )
        self.dimensions = dimensions
        self.metric = metric_s


@dataclass(kw_only=True)
class IvfKnnFactory(InnerIndexFactory):
    dimensions: int | None = None
    metric: Any = None
    n_clusters: int | None = None
    n_probe: int | None = None
    min_train: int = 4096
    embedder: Any = None

    def __post_init__(self):
        _check_factory_args(self.dimensions, self.embedder)

    def build_inner_index(self, data_column, metadata_column=None):
        return IvfKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            metric=self.metric,
            n_clusters=self.n_clusters,
            n_probe=self.n_probe,
            min_train=self.min_train,
            embedder=self.embedder,
        )
