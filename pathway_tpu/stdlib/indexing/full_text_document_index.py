"""Default full-text document index
(reference: stdlib/indexing/full_text_document_index.py)."""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex


def default_full_text_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = TantivyBM25(data_column, metadata_column)
    return DataIndex(data_table, inner)
