"""Metadata filtering — a JMESPath-subset evaluator
(reference: src/external_integration/mod.rs:252 JMESPath + glob filtering;
the jmespath crate is replaced by a small expression evaluator covering the
boolean queries the xpack emits: comparisons, &&/||/!, contains(),
globmatch(), dotted paths)."""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable

from pathway_tpu.internals.json import Json

_TOKEN = re.compile(
    r"""\s*(
        (?P<str>'(?:\\.|[^'\\])*'|`[^`]*`|"(?:\\.|[^"\\])*") |
        (?P<num>-?\d+(\.\d+)?) |
        (?P<op>&&|\|\||==|!=|<=|>=|<|>|!|\(|\)|,) |
        (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


def _tokenize(s: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"cannot tokenize filter at: {s[pos:]!r}")
        pos = m.end()
        for kind in ("str", "num", "op", "ident"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def eat(self, kind=None, val=None):
        k, v = self.toks[self.i]
        if kind and k != kind or (val is not None and v != val):
            raise ValueError(f"unexpected token {v!r}")
        self.i += 1
        return v

    def parse_or(self):
        left = self.parse_and()
        while self.peek() == ("op", "||"):
            self.eat()
            right = self.parse_and()
            l, r = left, right
            left = lambda md, l=l, r=r: bool(l(md)) or bool(r(md))
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.peek() == ("op", "&&"):
            self.eat()
            right = self.parse_not()
            l, r = left, right
            left = lambda md, l=l, r=r: bool(l(md)) and bool(r(md))
        return left

    def parse_not(self):
        if self.peek() == ("op", "!"):
            self.eat()
            inner = self.parse_not()
            return lambda md, i=inner: not bool(i(md))
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_atom()
        k, v = self.peek()
        if k == "op" and v in ("==", "!=", "<", "<=", ">", ">="):
            self.eat()
            right = self.parse_atom()

            def cmp(md, l=left, r=right, op=v):
                a, b = l(md), r(md)
                try:
                    if op == "==":
                        return a == b
                    if op == "!=":
                        return a != b
                    if a is None or b is None:
                        return False
                    if op == "<":
                        return a < b
                    if op == "<=":
                        return a <= b
                    if op == ">":
                        return a > b
                    if op == ">=":
                        return a >= b
                except TypeError:
                    return False

            return cmp
        return left

    def parse_atom(self):
        k, v = self.peek()
        if k == "op" and v == "(":
            self.eat()
            inner = self.parse_or()
            self.eat("op", ")")
            return inner
        if k == "str":
            self.eat()
            s = v[1:-1]
            if v[0] in ("'", '"'):
                # unescape ONLY the quote escapes the normalization emits;
                # other backslashes (windows paths) stay verbatim
                s = s.replace("\\'", "'").replace('\\"', '"')
            if v[0] == "`":
                # JMESPath backticks delimit JSON literals: `4` is the
                # number 4, `"x"` the string "x"; bare words fall back to
                # their raw text
                import json as _json

                try:
                    return lambda md, val=_json.loads(s): val
                except ValueError:
                    pass
            return lambda md, s=s: s
        if k == "num":
            self.eat()
            n = float(v) if "." in v else int(v)
            return lambda md, n=n: n
        if k == "ident":
            self.eat()
            if v in ("true", "True"):
                return lambda md: True
            if v in ("false", "False"):
                return lambda md: False
            if v in ("null", "None"):
                return lambda md: None
            if self.peek() == ("op", "("):
                # function call
                self.eat()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_or())
                    while self.peek() == ("op", ","):
                        self.eat()
                        args.append(self.parse_or())
                self.eat("op", ")")
                return _make_fn(v, args)
            path = v.split(".")
            return lambda md, p=path: _lookup(md, p)
        raise ValueError(f"unexpected token {v!r} in filter")


def _lookup(md: Any, path: list[str]) -> Any:
    cur = md
    for part in path:
        if isinstance(cur, Json):
            cur = cur.value
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    if isinstance(cur, Json):
        cur = cur.value
    return cur


def _make_fn(name: str, args: list[Callable]) -> Callable:
    if name == "contains":

        def contains(md):
            hay, needle = args[0](md), args[1](md)
            if hay is None:
                return False
            return needle in hay

        return contains
    if name == "globmatch":

        def globmatch(md):
            pattern, value = args[0](md), args[1](md)
            if value is None:
                return False
            return fnmatch.fnmatch(str(value), str(pattern))

        return globmatch
    if name == "to_number":

        def to_number(md):
            val = args[0](md)
            if val is None:
                return None
            try:
                f = float(val)
                return int(f) if f.is_integer() else f
            except (TypeError, ValueError):
                return None

        return to_number
    if name == "starts_with":
        return lambda md: str(args[1](md) or "").startswith(str(args[0](md)))
    raise ValueError(f"unknown filter function {name!r}")


def compile_filter(expr: str) -> Callable[[Any], bool]:
    """Compile a boolean metadata filter; returns a predicate over the
    metadata value (dict / Json / None)."""
    parser = _Parser(_tokenize(expr))
    fn = parser.parse_or()
    if parser.peek()[0] != "end":
        raise ValueError(f"trailing tokens in filter {expr!r}")

    def pred(md: Any) -> bool:
        if isinstance(md, Json):
            md = md.value
        if isinstance(md, str):
            import json as _json

            try:
                md = _json.loads(md)
            except ValueError:
                pass
        try:
            return bool(fn(md))
        except Exception:
            return False

    return pred
