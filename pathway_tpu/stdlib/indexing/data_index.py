"""DataIndex + InnerIndex — the retrieval layer
(reference: stdlib/indexing/data_index.py:278, InnerIndex:206).

InnerIndex answers queries with `_pw_index_reply` (a tuple of (id, score)
pairs, best first); DataIndex augments replies with the data table's columns
(collapsed to one tuple-valued row per query)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.engine.index_node import ExternalIndexNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    wrap_expr,
)
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.colnames import (
    _INDEX_REPLY,
    _MATCHED_ID,
    _SCORE,
)
import pathway_tpu.reducers as reducers


class InnerIndex(ABC):
    """A retrieval structure fed from ``data_column`` (+ optional metadata)
    answering queries with matched ids + scores."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ):
        self.data_column = data_column
        self.metadata_column = metadata_column

    @abstractmethod
    def query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: Any = 3,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table: ...

    @abstractmethod
    def query_as_of_now(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: Any = 3,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table: ...


class EngineInnerIndex(InnerIndex):
    """InnerIndex backed by a host index object driven by the engine's
    ExternalIndexNode (device work happens inside the index's search)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        index_factory: Callable[[], Any],
        embedder: Any = None,
    ):
        super().__init__(data_column, metadata_column)
        self.index_factory = index_factory
        self.embedder = embedder

    def _apply_embedder(self, col: ColumnExpression) -> ColumnExpression:
        if self.embedder is None:
            return col
        return self.embedder(col)

    def _query(self, query_column, number_of_matches, metadata_filter, as_of_now):
        data_table: Table = self.data_column.table
        data_exprs: dict[str, ColumnExpression] = {
            "_data": self._apply_embedder(self.data_column)
        }
        if self.metadata_column is not None:
            data_exprs["_meta"] = self.metadata_column
        data_prep = data_table._build_rowwise(data_exprs)

        query_table: Table = query_column.table
        q_exprs: dict[str, ColumnExpression] = {
            "_q": self._apply_embedder(query_column),
            "_k": wrap_expr(number_of_matches),
        }
        if metadata_filter is not None:
            q_exprs["_filter"] = metadata_filter
        query_prep = query_table._build_rowwise(q_exprs)

        node = ExternalIndexNode(
            data_prep._node,
            query_prep._node,
            self.index_factory,
            as_of_now=as_of_now,
        )
        return Table._from_node(
            node, {_INDEX_REPLY: dt.ANY_TUPLE}, query_table._universe
        )

    def query(
        self,
        query_column,
        *,
        number_of_matches: Any = 3,
        metadata_filter=None,
    ) -> Table:
        return self._query(
            query_column, number_of_matches, metadata_filter, as_of_now=False
        )

    def query_as_of_now(
        self,
        query_column,
        *,
        number_of_matches: Any = 3,
        metadata_filter=None,
    ) -> Table:
        return self._query(
            query_column, number_of_matches, metadata_filter, as_of_now=True
        )


@dataclass
class DataIndex:
    """Augments InnerIndex replies with the data table's columns
    (reference: stdlib/indexing/data_index.py:278)."""

    data_table: Table
    inner_index: InnerIndex

    def _repack(self, reply_table: Table, query_table: Table, collapse_rows: bool):
        base = reply_table.select(
            _qid=this.id, _reply=reply_table[_INDEX_REPLY]
        )
        flat = base.flatten(base._reply)  # one row per (query, match)
        flat2 = flat.select(
            _qid=this._qid,
            _ptr=this._reply.get(0),
            _score=this._reply.get(1),
        )
        data_rows = self.data_table.ix(flat2._ptr, optional=True)
        # reply columns carry the reference's public names so users can
        # select pw.right._pw_index_reply_score etc. (reference:
        # data_index.py _INDEX_REPLY schema)
        combined_exprs: dict[str, Any] = {
            "_qid": flat2._qid,
            _SCORE: flat2._score,
            _MATCHED_ID: flat2._ptr,
        }
        for c in self.data_table.column_names():
            combined_exprs[c] = data_rows[c]
        combined = flat2.select(**combined_exprs)
        if not collapse_rows:
            return query_table.join_left(
                combined, query_table.id == combined._qid
            )
        agg: dict[str, Any] = {"_qid": this._qid}
        for c in self.data_table.column_names():
            agg[c] = reducers.tuple(combined[c])
        agg[_SCORE] = reducers.tuple(combined[_SCORE])
        agg[_MATCHED_ID] = reducers.tuple(combined[_MATCHED_ID])
        collapsed = combined.groupby(
            combined._qid, sort_by=-combined[_SCORE]
        ).reduce(**agg)
        # every query gets a row: matchless queries collapse to EMPTY
        # tuples, not None (reference: test_no_match_is_empty_list)
        defaults = query_table.select(
            _qid=query_table.id,
            **{c: () for c in self.data_table.column_names()},
            **{_SCORE: (), _MATCHED_ID: ()},
        )
        full = defaults.update_rows(
            collapsed.with_id(collapsed._qid)
        )
        return query_table.join_left(
            full, query_table.id == full._qid, id=query_table.id
        )

    def query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnExpression | None = None,
    ):
        reply = self.inner_index.query(
            query_column,
            number_of_matches=number_of_matches,
            metadata_filter=metadata_filter,
        )
        return self._repack(reply, query_column.table, collapse_rows)

    def query_as_of_now(
        self,
        query_column: ColumnReference,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnExpression | None = None,
    ):
        reply = self.inner_index.query_as_of_now(
            query_column,
            number_of_matches=number_of_matches,
            metadata_filter=metadata_filter,
        )
        return self._repack(reply, query_column.table, collapse_rows)
