"""Host-side index objects backing the engine's ExternalIndexNode.

Replaces the reference's native index family (src/external_integration/):
- TpuDenseKnnIndex ← brute_force_knn_integration.rs + usearch_integration.rs
  (exact dense top-k on the MXU beats approximate HNSW on CPU at these sizes
  — the TPU-KNN result, arXiv 2206.14286)
- Bm25Index ← tantivy_integration.rs (host-side inverted index)
- LshKnnIndex ← stdlib/ml LSH candidate bucketing, projections on device
"""

from __future__ import annotations

import math
import os
import re
import time as _time
from collections import defaultdict
from typing import Any, Sequence

import numpy as np

from pathway_tpu.ops.knn import DeviceCorpus, dense_topk, sharded_topk
from pathway_tpu.stdlib.indexing._filters import compile_filter


def _as_vector(data: Any) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return data.astype(np.float32, copy=False)
    return np.asarray(list(data), dtype=np.float32)


class TpuDenseKnnIndex:
    """Exact dense KNN with device-resident corpus; optional mesh sharding."""

    def __init__(
        self,
        dimensions: int | None = None,
        metric: str = "cosine",
        reserved_space: int = 1024,
        mesh: Any = None,
        axis: str = "data",
        kernel: str = "auto",
    ):
        self.dim = dimensions
        self.metric = metric
        self.reserved = reserved_space
        self.mesh = mesh
        self.axis = axis
        self.corpus: DeviceCorpus | None = None
        self.metadata: dict[int, Any] = {}
        # scoring kernel: "xla" = dense_topk_prepared; "pallas" = the fused
        # Pallas block-top-k (ops/pallas_topk.py — only [B, nblk*k]
        # candidates return to HBM instead of the [B, N] score matrix).
        # "auto" follows PATHWAY_KNN_KERNEL, defaulting to xla.
        if kernel == "auto":
            kernel = os.environ.get("PATHWAY_KNN_KERNEL", "xla")
        if kernel not in ("xla", "pallas"):
            raise ValueError(f"unknown KNN kernel {kernel!r}")
        self.kernel = kernel
        # Surge Gate shape ladder: pad the query-batch dim to the next
        # power of two so the jitted top-k compiles once per bucket
        # instead of once per distinct concurrent-query count (the same
        # contract the encoder applies to embed batches).
        # PATHWAY_SERVING_SHAPE_LADDER=0 restores the seed's exact-shape
        # behavior (bench.py sets it, pre-build, for its unbatched
        # baseline phase). Resolved here — search() is the hot path.
        self.shape_ladder = (
            os.environ.get("PATHWAY_SERVING_SHAPE_LADDER", "1") != "0"
        )
        self._m_occupancy: dict[int, Any] = {}  # labeled child per bucket

    def _ensure(self, dim: int) -> DeviceCorpus:
        if self.corpus is None:
            sharding = valid_sharding = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                sharding = NamedSharding(self.mesh, P(self.axis, None))
                valid_sharding = NamedSharding(self.mesh, P(self.axis))
            cap = self.reserved
            if self.mesh is not None:
                n_dev = self.mesh.shape[self.axis]
                cap = max(cap, n_dev)
                cap = ((cap + n_dev - 1) // n_dev) * n_dev
            self.corpus = DeviceCorpus(
                dim, cap, sharding=sharding, valid_sharding=valid_sharding
            )
        return self.corpus

    def upsert(self, key: int, data: Any, metadata: Any) -> None:
        vec = _as_vector(data)
        corpus = self._ensure(len(vec))
        corpus.upsert(key, vec)
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key: int) -> None:
        if self.corpus is not None:
            self.corpus.remove(key)
        self.metadata.pop(key, None)

    # --- shard-ownership support (Shard Harbor, serving/replica.py) -------

    def __len__(self) -> int:
        return 0 if self.corpus is None else len(self.corpus)

    def keys(self) -> list[int]:
        """Resident corpus row keys."""
        c = self.corpus
        return [] if c is None else list(c.slot_of.keys())

    def filter_keys(self, pred) -> None:
        """Keep only keys matching ``pred`` and COMPACT the backing
        buffers to the kept count — ``remove()`` frees slots but keeps
        the host/device arrays at their old capacity, which would erase
        the ~1/S per-member memory win a sharded replica hydrates for."""
        c = self.corpus
        if c is None:
            self.metadata = {k: v for k, v in self.metadata.items() if pred(k)}
            return
        kept = [(k, s) for k, s in c.slot_of.items() if pred(k)]
        from pathway_tpu.ops.knn import DeviceCorpus

        fresh = DeviceCorpus(
            c.dim,
            max(len(kept), 1),
            sharding=c.sharding,
            valid_sharding=c.valid_sharding,
        )
        for key, slot in kept:
            fresh.upsert(key, c.host[slot])
        self.corpus = fresh
        self.metadata = {k: v for k, v in self.metadata.items() if pred(k)}

    def resident_bytes(self) -> int:
        """Host-side resident corpus bytes (the device mirror is the
        same shape) — the per-member memory evidence the shard×replica
        sweep records."""
        c = self.corpus
        if c is None:
            return 0
        return int(c.host.nbytes + c.valid_host.nbytes)

    # --- operator-snapshot support (reference: operator_snapshot.rs) ------
    # host-side content only; device arrays are re-uploaded lazily

    def state_dict(self) -> dict:
        c = self.corpus
        return {
            "metadata": self.metadata,
            "corpus": None
            if c is None
            else {
                "dim": c.dim,
                "capacity": c.capacity,
                "host": c.host,
                "valid_host": c.valid_host,
                "free": list(c.free),
                "slot_of": dict(c.slot_of),
                "key_of": dict(c.key_of),
            },
        }

    def load_state(self, state: dict) -> None:
        self.metadata = dict(state["metadata"])
        cs = state["corpus"]
        self.corpus = None
        if cs is None:
            return
        c = self._ensure(cs["dim"])  # fresh corpus with current sharding
        if c.capacity == cs["capacity"]:
            c.host = cs["host"]
            c.valid_host = cs["valid_host"]
            c.free = list(cs["free"])
            c.slot_of = dict(cs["slot_of"])
            c.key_of = dict(cs["key_of"])
            c._dirty = True
        else:  # capacity alignment changed between versions: re-upsert
            for key, slot in cs["slot_of"].items():
                c.upsert(key, cs["host"][slot])

    def search(self, queries: Sequence[tuple[Any, int, Any]]):
        if self.corpus is None or len(self.corpus) == 0 or not queries:
            return [() for _ in queries]
        qmat = np.stack([_as_vector(q) for q, _k, _f in queries])
        n_q = qmat.shape[0]
        bucket = n_q
        if self.shape_ladder:
            bucket = 1 << max(0, n_q - 1).bit_length()
            if bucket != n_q:
                qmat = np.pad(qmat, ((0, bucket - n_q), (0, 0)))
            child = self._m_occupancy.get(bucket)
            if child is None:
                from pathway_tpu.serving.metrics import occupancy_histogram

                child = occupancy_histogram().labels("knn", str(bucket))
                self._m_occupancy[bucket] = child
            child.observe(n_q / bucket)
        max_k = max(int(k) for _q, k, _f in queries)
        has_filter = any(f is not None for _q, _k, f in queries)
        # oversample when filtering so post-filter still fills k
        eff_k = min(
            len(self.corpus), max_k * 4 if has_filter else max_k
        )
        _rt0 = _time.perf_counter()
        if self.mesh is not None:
            corpus_arr, valid = self.corpus.device_arrays()
            scores, idx = sharded_topk(
                qmat,
                corpus_arr,
                valid,
                eff_k,
                mesh=self.mesh,
                axis=self.axis,
                metric=self.metric,
            )
        else:
            from pathway_tpu.ops.knn import dense_topk_prepared

            # f32 end to end: the inner-index path serves RAG retrieval on
            # modest corpora where exact reference-parity scores matter;
            # the bulk bench path keeps bf16 on the MXU
            prep, c2, valid = self.corpus.prepared_arrays(
                self.metric, bf16=False
            )
            scores = idx = None
            if self.kernel == "pallas" and self.metric in ("cosine", "dot"):
                from pathway_tpu.ops import pallas_topk as pt

                if pt.supported(prep.shape[0], eff_k):
                    import jax

                    interpret = jax.devices()[0].platform == "cpu"
                    scores, idx = pt.pallas_dense_topk(
                        qmat,
                        prep,
                        valid,
                        eff_k,
                        metric=self.metric,
                        interpret=interpret,
                    )
            if scores is None:
                scores, idx = dense_topk_prepared(
                    qmat, prep, c2, valid, eff_k, metric=self.metric,
                    bf16=False,
                )
        scores = np.asarray(scores, dtype=np.float64)[:n_q]
        idx = np.asarray(idx)[:n_q]
        # Tick Scope roofline, family "topk": analytic FLOPs (the score
        # matmul dominates: 2*B*N*D per call) over measured wall with the
        # host sync included. Registered analytically because the pallas
        # kernel's interpret-mode lowering has no XLA cost model.
        try:
            from pathway_tpu.observability import tickscope as _ts

            _n, _d = len(self.corpus), qmat.shape[1]
            _key = f"topk_b{qmat.shape[0]}_n{_n}_d{_d}_k{eff_k}"
            _rl = _ts.roofline()
            if not _rl.known("topk", _key):
                _rl.register(
                    "topk",
                    _key,
                    2.0 * qmat.shape[0] * _n * _d,
                    source="analytic",
                )
            _rl.observe("topk", _key, _time.perf_counter() - _rt0)
        except Exception:  # pragma: no cover - defensive
            pass
        if self.metric == "cosine":
            # reference USearch COS scores are -(1 - cos): negative
            # distances, not raw similarities
            scores = scores - 1.0
        out = []
        for qi, (_q, k, flt) in enumerate(queries):
            if int(k) <= 0:
                out.append(())  # k=0 means no matches, not one
                continue
            pred = compile_filter(flt) if flt else None
            matches = []
            for j in range(idx.shape[1]):
                slot = idx[qi, j]
                if slot < 0:
                    break
                key = self.corpus.key_of.get(int(slot))
                if key is None:
                    continue
                if pred is not None and not pred(self.metadata.get(key)):
                    continue
                matches.append((key, float(scores[qi, j])))
                if len(matches) >= int(k):
                    break
            out.append(tuple(matches))
        return out


_WORD = re.compile(r"\w+", re.UNICODE)


class Bm25Index:
    """BM25 full-text index (reference: tantivy_integration.rs:16)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b
        self.docs: dict[int, dict[str, int]] = {}
        self.doc_len: dict[int, int] = {}
        self.postings: dict[str, dict[int, int]] = defaultdict(dict)
        self.metadata: dict[int, Any] = {}

    @staticmethod
    def _tokens(text: str) -> list[str]:
        return [w.lower() for w in _WORD.findall(str(text))]

    def upsert(self, key: int, data: Any, metadata: Any) -> None:
        self.remove(key)
        tf: dict[str, int] = defaultdict(int)
        toks = self._tokens(data)
        for tok in toks:
            tf[tok] += 1
        self.docs[key] = dict(tf)
        self.doc_len[key] = len(toks)
        for tok, c in tf.items():
            self.postings[tok][key] = c
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key: int) -> None:
        tf = self.docs.pop(key, None)
        if tf:
            for tok in tf:
                self.postings[tok].pop(key, None)
        self.doc_len.pop(key, None)
        self.metadata.pop(key, None)

    def search(self, queries: Sequence[tuple[Any, int, Any]]):
        n = len(self.docs)
        if n == 0:
            return [() for _ in queries]
        avg_len = sum(self.doc_len.values()) / n
        out = []
        for qtext, k, flt in queries:
            pred = compile_filter(flt) if flt else None
            scores: dict[int, float] = defaultdict(float)
            for tok in self._tokens(qtext):
                plist = self.postings.get(tok)
                if not plist:
                    continue
                idf = math.log(1 + (n - len(plist) + 0.5) / (len(plist) + 0.5))
                for doc, tf in plist.items():
                    dl = self.doc_len[doc] or 1
                    scores[doc] += (
                        idf
                        * tf
                        * (self.k1 + 1)
                        / (tf + self.k1 * (1 - self.b + self.b * dl / avg_len))
                    )
            ranked = sorted(scores.items(), key=lambda kv: -kv[1])
            matches = []
            for doc, s in ranked:
                if pred is not None and not pred(self.metadata.get(doc)):
                    continue
                matches.append((doc, float(s)))
                if len(matches) >= int(k):
                    break
            out.append(tuple(matches))
        return out


class LshKnnIndex:
    """LSH-bucketed ANN: device projections pick candidate buckets, exact
    rerank within candidates (reference: stdlib/ml/classifiers/_lsh.py)."""

    def __init__(
        self,
        dimensions: int,
        n_or: int = 8,
        n_and: int = 4,
        bucket_length: float = 4.0,
        metric: str = "l2sq",
        seed: int = 42,
    ):
        from pathway_tpu.ops.lsh import make_projections

        self.dim = dimensions
        self.n_or = n_or
        self.bucket_length = bucket_length
        self.metric = metric
        self.planes, self.offsets = make_projections(
            dimensions, n_or, n_and, bucket_length, seed
        )
        self.buckets: list[dict[int, set[int]]] = [
            defaultdict(set) for _ in range(n_or)
        ]
        self.vectors: dict[int, np.ndarray] = {}
        self.metadata: dict[int, Any] = {}

    def _bucket_ids(self, vecs: np.ndarray) -> np.ndarray:
        from pathway_tpu.ops.lsh import lsh_buckets

        return np.asarray(
            lsh_buckets(vecs, self.planes, self.offsets, self.bucket_length)
        )

    def upsert(self, key: int, data: Any, metadata: Any) -> None:
        vec = _as_vector(data)
        self.remove(key)
        self.vectors[key] = vec
        ids = self._bucket_ids(vec[None])[0]
        for t, b in enumerate(ids):
            self.buckets[t][int(b)].add(key)
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key: int) -> None:
        vec = self.vectors.pop(key, None)
        if vec is not None:
            ids = self._bucket_ids(vec[None])[0]
            for t, b in enumerate(ids):
                self.buckets[t][int(b)].discard(key)
        self.metadata.pop(key, None)

    def state_dict(self) -> dict:
        # planes/offsets are deterministic from the constructor args, so
        # only the mutable content snapshots (jax arrays stay out)
        return {
            "buckets": [dict(b) for b in self.buckets],
            "vectors": self.vectors,
            "metadata": self.metadata,
        }

    def load_state(self, state: dict) -> None:
        self.buckets = [defaultdict(set, b) for b in state["buckets"]]
        self.vectors = dict(state["vectors"])
        self.metadata = dict(state["metadata"])

    def search(self, queries: Sequence[tuple[Any, int, Any]]):
        if not self.vectors:
            return [() for _ in queries]
        qmat = np.stack([_as_vector(q) for q, _k, _f in queries])
        all_ids = self._bucket_ids(qmat)
        out = []
        for qi, (q, k, flt) in enumerate(queries):
            pred = compile_filter(flt) if flt else None
            candidates: set[int] = set()
            for t, b in enumerate(all_ids[qi]):
                candidates |= self.buckets[t].get(int(b), set())
            if not candidates:
                out.append(())
                continue
            qv = _as_vector(q)
            scored = []
            for key in candidates:
                if pred is not None and not pred(self.metadata.get(key)):
                    continue
                v = self.vectors[key]
                if self.metric == "cosine":
                    # same convention as the dense backends: negative
                    # cosine distance (cos - 1), exact match scores 0
                    s = float(
                        np.dot(qv, v)
                        / ((np.linalg.norm(qv) * np.linalg.norm(v)) + 1e-30)
                    ) - 1.0
                else:
                    s = -float(np.sum((qv - v) ** 2))
                scored.append((key, s))
            scored.sort(key=lambda kv: -kv[1])
            out.append(tuple(scored[: int(k)]))
        return out


class IvfKnnIndex:
    """Two-level IVF KNN — the >HBM scale-out tier (design note in
    ops/ivf.py; reference counterpart: usearch HNSW,
    src/external_integration/usearch_integration.rs:20). Coarse matmul
    quantization picks nprobe inverted lists, exact matmul scoring ranks
    their members. Below ``min_train`` points (and until training) the
    index scores exactly over everything, so small corpora behave
    identically to the brute-force index."""

    def __init__(
        self,
        dimensions: int | None = None,
        metric: str = "cosine",
        n_clusters: int | None = None,
        n_probe: int | None = None,
        min_train: int = 4096,
        train_sample: int = 20000,
        seed: int = 0,
    ):
        if metric not in ("cosine", "dot", "l2sq"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dim = dimensions
        self.metric = metric
        self.n_clusters = n_clusters
        self.n_probe = n_probe
        self.min_train = min_train
        self.train_sample = train_sample
        self.seed = seed
        self.vecs: dict[int, np.ndarray] = {}
        self.metadata: dict[int, Any] = {}
        self.centroids: np.ndarray | None = None
        self.lists: dict[int, set[int]] = {}
        self.key_cluster: dict[int, int] = {}
        self._pending: list[int] = []  # keys awaiting cluster assignment
        self._trained_size = 0

    # --- maintenance ------------------------------------------------------

    def _space(self, v: np.ndarray) -> np.ndarray:
        """Clustering space: normalized for cosine (so L2 ~ angle), raw
        otherwise."""
        if self.metric == "cosine":
            return v / (np.linalg.norm(v, axis=-1, keepdims=True) + 1e-30)
        return v

    def upsert(self, key: int, data: Any, metadata: Any) -> None:
        vec = _as_vector(data)
        if self.dim is not None and len(vec) != self.dim:
            raise ValueError(
                f"IvfKnnIndex: expected {self.dim}-dim vectors, "
                f"got {len(vec)}"
            )
        self.remove(key)
        self.vecs[key] = vec
        if metadata is not None:
            self.metadata[key] = metadata
        self._pending.append(key)

    def remove(self, key: int) -> None:
        self.vecs.pop(key, None)
        self.metadata.pop(key, None)
        c = self.key_cluster.pop(key, None)
        if c is not None:
            self.lists.get(c, set()).discard(key)

    def _maybe_train(self) -> None:
        from pathway_tpu.ops.ivf import train_centroids

        n = len(self.vecs)
        if n < self.min_train:
            return
        if self.centroids is not None and n < 4 * self._trained_size:
            return
        rng = np.random.default_rng(self.seed)
        keys = list(self.vecs.keys())
        if len(keys) > self.train_sample:
            keys = [
                keys[i]
                for i in rng.choice(
                    len(keys), size=self.train_sample, replace=False
                )
            ]
        sample = self._space(np.stack([self.vecs[k] for k in keys]))
        n_clusters = self.n_clusters or max(
            8, int(round(math.sqrt(n) / 8)) * 8
        )
        self.centroids = train_centroids(
            sample, n_clusters, seed=self.seed
        )
        # reassign EVERYTHING under the new centroids
        self.lists = {}
        self.key_cluster = {}
        self._pending = list(self.vecs.keys())
        self._trained_size = n

    def _flush_assign(self) -> None:
        from pathway_tpu.ops.ivf import assign_clusters

        if self.centroids is None:
            return  # keep pending until training happens
        if not self._pending:
            return
        keys = [k for k in self._pending if k in self.vecs]
        self._pending = []
        if not keys:
            return
        x = self._space(np.stack([self.vecs[k] for k in keys]))
        assign = assign_clusters(x, self.centroids)
        for k, c in zip(keys, assign.tolist()):
            self.key_cluster[k] = c
            self.lists.setdefault(c, set()).add(k)

    # --- snapshots --------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "vecs": self.vecs,
            "metadata": self.metadata,
            "centroids": self.centroids,
            "key_cluster": self.key_cluster,
            "trained_size": self._trained_size,
        }

    def load_state(self, state: dict) -> None:
        self.vecs = dict(state["vecs"])
        self.metadata = dict(state["metadata"])
        self.centroids = state["centroids"]
        self._trained_size = int(state.get("trained_size", 0))
        self.key_cluster = dict(state["key_cluster"])
        self.lists = {}
        for k, c in self.key_cluster.items():
            self.lists.setdefault(c, set()).add(k)
        self._pending = [k for k in self.vecs if k not in self.key_cluster]

    # --- query ------------------------------------------------------------

    def _score(self, q: np.ndarray, keys: list[int]) -> np.ndarray:
        mat = np.stack([self.vecs[k] for k in keys]).astype(np.float32)
        qv = q.astype(np.float32)
        if self.metric == "cosine":
            qv = qv / (np.linalg.norm(qv) + 1e-30)
            mat = mat / (
                np.linalg.norm(mat, axis=1, keepdims=True) + 1e-30
            )
            return mat @ qv - 1.0  # reference COS convention: -(1 - cos)
        if self.metric == "l2sq":
            d = mat - qv[None, :]
            return -np.sum(d * d, axis=1)
        return mat @ qv

    def search(self, queries: Sequence[tuple[Any, int, Any]]):
        if not queries:
            return []
        if not self.vecs:
            return [() for _ in queries]
        self._maybe_train()
        self._flush_assign()
        out = []
        for q, k, flt in queries:
            if int(k) <= 0:
                out.append(())
                continue
            qv = _as_vector(q)
            if self.centroids is None:
                cand = list(self.vecs.keys())  # exact below min_train
            else:
                qs = self._space(qv[None, :]).astype(np.float32)
                c32 = self.centroids.astype(np.float32)
                d = (
                    np.sum(c32 * c32, axis=1)
                    - 2.0 * (qs @ c32.T)[0]
                )
                n_probe = self.n_probe or max(
                    1, int(round(math.sqrt(len(c32))))
                )
                n_probe = min(n_probe, len(c32))
                probes = np.argpartition(d, n_probe - 1)[:n_probe]
                cand = [
                    key
                    for c in probes.tolist()
                    for key in self.lists.get(c, ())
                ]
                if not cand:
                    cand = list(self.vecs.keys())
            scores = self._score(qv, cand)
            order = np.argsort(-scores, kind="stable")
            pred = compile_filter(flt) if flt else None
            matches = []
            for j in order.tolist():
                key = cand[j]
                if pred is not None and not pred(self.metadata.get(key)):
                    continue
                matches.append((key, float(scores[j])))
                if len(matches) >= int(k):
                    break
            out.append(tuple(matches))
        return out
