"""Retriever factory ABCs (reference: stdlib/indexing/retrievers.py:7-17)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex


class AbstractRetrieverFactory(ABC):
    @abstractmethod
    def build_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnExpression | None = None,
    ) -> DataIndex: ...


class InnerIndexFactory(AbstractRetrieverFactory):
    @abstractmethod
    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex: ...

    def build_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnExpression | None = None,
    ) -> DataIndex:
        inner = self.build_inner_index(data_column, metadata_column)
        return DataIndex(data_table, inner)
