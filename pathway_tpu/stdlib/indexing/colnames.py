"""Internal column names used by the indexing machinery
(reference: stdlib/indexing/colnames.py)."""

_INDEX_REPLY = "_pw_index_reply"
_MATCHED_ID = "_pw_index_reply_id"
_SCORE = "_pw_index_reply_score"
_QUERY_ID = "_pw_query_id"
_NO_OF_MATCHES = "_pw_index_number_of_matches"
