"""BM25 full-text inner index (reference: stdlib/indexing/bm25.py:41
TantivyBM25 over the tantivy crate; here a host-side inverted index)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing._index_impls import Bm25Index
from pathway_tpu.stdlib.indexing.data_index import EngineInnerIndex
from pathway_tpu.stdlib.indexing.retrievers import InnerIndexFactory


class TantivyBM25(EngineInnerIndex):
    """Reference-parity name; host-side BM25 scoring."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
        k1: float = 1.2,
        b: float = 0.75,
    ):
        super().__init__(
            data_column,
            metadata_column,
            index_factory=lambda: Bm25Index(k1=k1, b=b),
        )


@dataclass(kw_only=True)
class TantivyBM25Factory(InnerIndexFactory):
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_inner_index(self, data_column, metadata_column=None):
        return TantivyBM25(
            data_column,
            metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )
