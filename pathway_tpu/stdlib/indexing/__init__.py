from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnnMetricKind,
    USearchMetricKind,
    BruteForceKnn,
    BruteForceKnnFactory,
    IvfKnn,
    IvfKnnFactory,
    LshKnn,
    LshKnnFactory,
    TpuKnn,
    TpuKnnFactory,
    USearchKnn,
    UsearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory
from pathway_tpu.stdlib.indexing.vector_document_index import (
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)

__all__ = [
    "BruteForceKnnMetricKind",
    "USearchMetricKind",
    "DataIndex",
    "InnerIndex",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "IvfKnn",
    "IvfKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "TpuKnn",
    "TpuKnnFactory",
    "USearchKnn",
    "UsearchKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexFactory",
    "AbstractRetrieverFactory",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "default_full_text_document_index",
]
