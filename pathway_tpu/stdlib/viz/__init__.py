"""pw.viz — table visualization helpers.

TPU-native counterpart of the reference's viz stdlib
(reference: python/pathway/stdlib/viz/ — Bokeh live plots in plotting.py,
DataFrame-styled table snapshots in table_viz.py). Bokeh is not in this
image, so `plot` degrades to a clear error while `show`/`table_viz` render
through pandas/rich, which are available.
"""

from __future__ import annotations

from typing import Any, Callable


def table_viz(table: Any, **kwargs: Any):
    """Render the table's current static result as a styled DataFrame
    (reference: stdlib/viz/table_viz.py)."""
    from pathway_tpu.debug import table_to_pandas

    return table_to_pandas(table, include_id=False)


def show(table: Any, **kwargs: Any) -> None:
    """Print the table's current result (rich table when on a tty)."""
    try:
        from rich.console import Console
        from rich.table import Table as RichTable

        df = table_viz(table)
        rt = RichTable()
        for c in df.columns:
            rt.add_column(str(c))
        for _idx, row in df.iterrows():
            rt.add_row(*[str(v) for v in row])
        Console().print(rt)
    except ImportError:
        from pathway_tpu.debug import compute_and_print

        compute_and_print(table, include_id=False)


def plot(table: Any, plotting_function: Callable | None = None, **kwargs: Any):
    """Bokeh plot of a table's computed result
    (reference: stdlib/viz/plotting.py). Requires `bokeh`, which is not
    baked into this image."""
    try:
        import bokeh  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pw.viz.plot requires bokeh, which is not installed in this "
            "environment; use pw.viz.show / pw.live(table).to_pandas instead"
        ) from e
    df = table_viz(table)
    if plotting_function is not None:
        return plotting_function(df)
    from bokeh.plotting import figure

    return figure()
