import importlib

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
    "viz",
]


def __getattr__(name: str):
    if name in __all__:
        module = importlib.import_module(f"pathway_tpu.stdlib.{name}")
        globals()[name] = module
        return module
    raise AttributeError(name)
