"""AsyncTransformer (reference: stdlib/utils/async_transformer.py:281):
fully-async request/response operator — rows go out to `invoke`, results come
back as a new table."""

from __future__ import annotations

import asyncio
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.table import Table


class _Result:
    def __init__(self, table: Table):
        self.successful = table
        self.failed = table.filter(
            expr_mod.ColumnConstExpression(False)  # placeholder: no failures split
        )
        self.finished = table


class AsyncTransformer:
    """Subclass and define ``output_schema`` and ``async def invoke(self,
    **kwargs) -> dict``."""

    output_schema: Any = None

    def __init__(self, input_table: Table, *, instance: Any = None, **kwargs):
        self._input_table = input_table
        self._instance = instance
        assert self.output_schema is not None, "define output_schema"

    def with_options(self, **kwargs) -> "AsyncTransformer":
        return self

    async def invoke(self, **kwargs) -> dict:
        raise NotImplementedError

    @property
    def successful(self) -> Table:
        return self.result.successful

    @property
    def failed(self) -> Table:
        return self.result.failed

    @property
    def finished(self) -> Table:
        return self.result.finished

    @property
    def result(self) -> _Result:
        if not hasattr(self, "_result"):
            self._result = _Result(self._build())
        return self._result

    def _build(self) -> Table:
        table = self._input_table
        out_names = list(self.output_schema.column_names())
        invoke = self.invoke

        async def call(*vals):
            kwargs = dict(zip(table.column_names(), vals))
            return await invoke(**kwargs)

        e = expr_mod.AsyncApplyExpression(
            call,
            dict,
            False,
            True,
            tuple(table[n] for n in table.column_names()),
            {},
        )
        packed = table.select(_result=e)
        exprs = {
            n: expr_mod.GetExpression(packed._result, n, None, True)
            for n in out_names
        }
        out = packed.select(**exprs)
        dtypes = dict(self.output_schema.dtypes())
        return out.update_types(**{n: dtypes[n] for n in out_names})
