"""AsyncTransformer (reference: stdlib/utils/async_transformer.py:281-511):
fully-async request/response operator — each input row is handed to the
user's ``invoke`` coroutine; results come back as a table with a
``_async_status`` column and ``successful`` / ``failed`` / ``finished``
views.

Design vs the reference: the reference routes results through a Python
connector back into the engine (a second input), because timely workers
cannot block on a future. The microbatch engine's totally-ordered tick can
await the whole batch, so this implementation is a single custom operator:
all rows of a tick run concurrently on one event loop (bounded by
``capacity``), and instance consistency is enforced per tick — a failure
poisons every same-instance row at the same or later logical time, exactly
the reference's "-FAILURE-" promotion rule. Consequently ``finished``
never observes "-PENDING-" rows (a timing artifact of the reference's
round-trip architecture, not part of its contract)."""

from __future__ import annotations

import asyncio
import inspect
import re
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import Node, NodeExec
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import udfs
from pathway_tpu.internals.table import Table

_ASYNC_STATUS_COLUMN = "_async_status"
_SUCCESS = "-SUCCESS-"
_FAILURE = "-FAILURE-"


class _AsyncTransformNode(Node):
    def __init__(self, input_node: Node, transformer: "AsyncTransformer"):
        out_cols = list(transformer.output_schema.column_names()) + [
            _ASYNC_STATUS_COLUMN
        ]
        super().__init__([input_node], out_cols)
        self.transformer = transformer

    def make_exec(self):
        return _AsyncTransformExec(self)


class _AsyncTransformExec(NodeExec):
    def __init__(self, node: _AsyncTransformNode):
        super().__init__(node)
        tr = node.transformer
        in_cols = node.inputs[0].column_names
        self.in_cols = in_cols
        self.inst_idx = tr._instance_idx(in_cols)
        self.out_names = list(tr.output_schema.column_names())
        # instance value -> poisoned from some logical time onward
        self.failed_instances: set = set()
        self.emitted: dict[int, tuple] = {}
        self._opened = False

    def state_dict(self):
        return {
            "failed_instances": self.failed_instances,
            "emitted": self.emitted,
        }

    def load_state(self, state):
        self.failed_instances = state["failed_instances"]
        self.emitted = state["emitted"]

    def _run_batch(self, rows: list[tuple]) -> list[Any]:
        """Run invoke for every row concurrently; returns a result dict or
        an Exception per row."""
        tr = self.node.transformer
        invoke = tr._prepared_invoke()
        capacity = tr._capacity

        async def run_all():
            sem = asyncio.Semaphore(capacity) if capacity else None

            async def one(kwargs):
                if sem is None:
                    return await invoke(**kwargs)
                async with sem:
                    return await invoke(**kwargs)

            return await asyncio.gather(
                *[one(kw) for kw in rows], return_exceptions=True
            )

        return udfs.run_async_blocking(run_all)

    def process(self, t, inputs):
        tr = self.node.transformer
        out_rows: list[tuple[int, int, tuple]] = []
        # one pass over the WHOLE tick: instance demotion must see every
        # batch of this logical time, and an insert+retract within the
        # tick must cancel instead of leaving a ghost result
        additions: dict[int, tuple[Any, dict]] = {}
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                inst = (
                    vals[self.inst_idx] if self.inst_idx is not None else k
                )
                if d > 0:
                    kwargs = {
                        n: v
                        for n, v in zip(self.in_cols, vals)
                        if n != "_instance"
                    }
                    additions[k] = (inst, kwargs)
                elif k in additions:
                    del additions[k]  # net-zero within the tick
                else:
                    old = self.emitted.pop(k, None)
                    if old is not None:
                        out_rows.append((k, -1, old))
        if additions:
            if not self._opened:
                tr.open()
                self._opened = True
            items = list(additions.items())
            results = self._run_batch([kw for _k, (_i, kw) in items])
            # first pass: record which instances failed at this time
            statuses = []
            for (_k, (inst, _kw)), res in zip(items, results):
                ok = not isinstance(res, BaseException)
                if ok:
                    try:
                        tr._check_result(res)
                    except Exception:
                        ok = False
                if not ok:
                    self.failed_instances.add(inst)
                statuses.append(ok)
            # second pass: a success whose instance failed at <= this time
            # is demoted to FAILURE (reference `failed` contract)
            for (k, (inst, _kw)), res, ok in zip(items, results, statuses):
                if ok and inst not in self.failed_instances:
                    vals_out = tuple(res[n] for n in self.out_names) + (
                        _SUCCESS,
                    )
                else:
                    vals_out = tuple(None for _ in self.out_names) + (
                        _FAILURE,
                    )
                old = self.emitted.get(k)
                if old is not None:
                    out_rows.append((k, -1, old))
                out_rows.append((k, 1, vals_out))
                self.emitted[k] = vals_out
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]

    def on_end(self):
        if self._opened:
            self.node.transformer.close()
        return []


class AsyncTransformer:
    """Subclass with ``output_schema`` (class kwarg or attribute) and an
    ``async def invoke(self, **kwargs) -> dict`` matching the input columns
    (reference: python/pathway/stdlib/utils/async_transformer.py:281)."""

    output_schema: Any = None

    def __init_subclass__(cls, /, output_schema: Any = None, **kwargs):
        super().__init_subclass__(**kwargs)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(
        self,
        input_table: Table,
        *,
        instance: Any = None,
        autocommit_duration_ms: int | None = 1500,
        **kwargs,
    ):
        assert self.output_schema is not None, "define output_schema"
        self._check_signature(input_table)
        if instance is not None:
            input_table = input_table.with_columns(_instance=instance)
        self._input_table = input_table
        self._has_instance = instance is not None
        self._capacity: int | None = None
        self._timeout: float | None = None
        self._retry_strategy: udfs.AsyncRetryStrategy | None = None
        self._cache_strategy: udfs.CacheStrategy | None = None
        self._prepared: Any = None

    # --- configuration -----------------------------------------------------

    def with_options(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
    ) -> "AsyncTransformer":
        if capacity is not None:
            self._capacity = capacity
        if timeout is not None:
            self._timeout = timeout
        if retry_strategy is not None:
            self._retry_strategy = retry_strategy
        if cache_strategy is not None:
            self._cache_strategy = cache_strategy
        self._prepared = None
        return self

    def open(self) -> None:
        """One-time setup before the first invoke (reference parity)."""

    def close(self) -> None:
        """Cleanup after the run finishes (reference parity)."""

    async def invoke(self, **kwargs) -> dict:
        raise NotImplementedError

    # --- internals ----------------------------------------------------------

    def _check_signature(self, input_table: Table) -> None:
        sig = inspect.signature(self.invoke)
        try:
            sig.bind(**{n: None for n in input_table.column_names()})
        except TypeError as e:
            msg = str(e)
            if m := re.match("got an unexpected keyword argument '(.+)'", msg):
                raise TypeError(
                    f"Input table has a column {m[1]!r} but it is not "
                    "present on the argument list of the invoke method."
                )
            if m := re.match("missing a required argument: '(.+)'", msg):
                raise TypeError(
                    f"Column {m[1]!r} is present on the argument list of "
                    "the invoke method but it is not present in the "
                    "input_table."
                )
            raise

    def _check_result(self, result: Any) -> None:
        if not isinstance(result, dict) or set(result.keys()) != set(
            self.output_schema.column_names()
        ):
            raise ValueError(
                f"invoke result {result!r} does not match output_schema "
                f"columns {list(self.output_schema.column_names())}"
            )

    def _instance_idx(self, in_cols: list[str]) -> int | None:
        return in_cols.index("_instance") if self._has_instance else None

    def _prepared_invoke(self):
        if self._prepared is None:
            fn = self.invoke
            if self._cache_strategy is not None:
                inner0 = fn
                memo: dict = {}

                async def fn_cached(**kwargs):
                    key = tuple(sorted(kwargs.items()))
                    if key in memo:
                        return memo[key]
                    result = await inner0(**kwargs)
                    memo[key] = result
                    return result

                fn = fn_cached
            if self._retry_strategy is not None:
                fn = udfs.with_retry_strategy(fn, self._retry_strategy)
            if self._timeout is not None:
                inner = fn

                async def timed(**kwargs):
                    return await asyncio.wait_for(
                        inner(**kwargs), timeout=self._timeout
                    )

                fn = timed
            self._prepared = fn
        return self._prepared

    # --- result views -------------------------------------------------------

    @property
    def output_table(self) -> Table:
        """All rows with their "-SUCCESS-"/"-FAILURE-" status column."""
        if not hasattr(self, "_output_table"):
            node = _AsyncTransformNode(self._input_table._node, self)
            dtypes = {
                n: dt.Optional_(d)
                for n, d in self.output_schema.dtypes().items()
            }
            dtypes[_ASYNC_STATUS_COLUMN] = dt.STR
            self._output_table = Table._from_node(
                node, dtypes, self._input_table._universe.subset()
            )
        return self._output_table

    @property
    def successful(self) -> Table:
        out = self.output_table
        res = out.filter(
            out[_ASYNC_STATUS_COLUMN] == _SUCCESS
        ).without(_ASYNC_STATUS_COLUMN)
        return res.update_types(**dict(self.output_schema.dtypes()))

    @property
    def failed(self) -> Table:
        out = self.output_table
        return out.filter(
            out[_ASYNC_STATUS_COLUMN] == _FAILURE
        ).without(_ASYNC_STATUS_COLUMN)

    @property
    def finished(self) -> Table:
        return self.output_table

    @property
    def result(self) -> "AsyncTransformer":
        return self
