"""``@pw.pandas_transformer`` — lift a pandas.DataFrame function into a
table operator (reference: stdlib/utils/pandas_transformer.py:124).

Input universes become DataFrame indexes; the function's output index is
the output universe (must be unique integers). Under the microbatch
engine this is a whole-table operator: any input tick re-derives the
DataFrame computation and only changed output rows are emitted."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import Node, NodeExec
from pathway_tpu.internals.errors import record_error
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class _PandasTransformNode(Node):
    def __init__(self, input_nodes, func: Callable, output_schema):
        super().__init__(
            list(input_nodes), list(output_schema.column_names())
        )
        self.func = func
        self.output_schema = output_schema

    def make_exec(self):
        return _PandasTransformExec(self)


class _PandasTransformExec(NodeExec):
    def __init__(self, node: _PandasTransformNode):
        super().__init__(node)
        self.states: list[dict[int, tuple]] = [{} for _ in node.inputs]
        self.emitted: dict[int, tuple] = {}

    def process(self, t, inputs):
        import pandas as pd

        changed = False
        for state, batches in zip(self.states, inputs):
            for b in batches:
                for k, d, vals in b.iter_rows():
                    changed = True
                    if d > 0:
                        state[k] = vals
                    else:
                        state.pop(k, None)
        if not changed:
            return []
        frames = []
        for state, inp in zip(self.states, self.node.inputs):
            keys = list(state.keys())
            data = {
                n: [state[k][i] for k in keys]
                for i, n in enumerate(inp.column_names)
            }
            frames.append(pd.DataFrame(data, index=keys))
        out_names = self.node.column_names
        new_vals: dict[int, tuple] = {}
        try:
            result = self.node.func(*frames)
        except Exception as exc:
            record_error(exc, str(self.node))
            result = None
        if result is not None:
            if not isinstance(result, pd.DataFrame):
                result = pd.DataFrame(result)
            # a shape mismatch is a programming error, not a data error:
            # fail the run instead of silently padding or staling
            if len(result.columns) != len(out_names):
                raise ValueError(
                    f"pandas_transformer returned {len(result.columns)} "
                    f"column(s) but output_schema declares "
                    f"{len(out_names)}: {list(out_names)}"
                )
            if result.index.has_duplicates:
                raise ValueError(
                    "pandas_transformer output index must be unique (it "
                    "becomes the output universe)"
                )
            result.columns = list(out_names)
            for key, row in result.iterrows():
                new_vals[int(key)] = tuple(row[n] for n in out_names)
        else:
            new_vals = dict(self.emitted)  # error in user fn: keep output
        from pathway_tpu.engine.batch import _values_eq

        out_rows: list[tuple[int, int, tuple]] = []
        for k in set(self.emitted) | set(new_vals):
            old = self.emitted.get(k)
            new = new_vals.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, out_names)]


def pandas_transformer(
    output_schema: Any, output_universe: str | int | None = None
):
    """Decorator turning a pandas-DataFrame function into a table
    transformer (reference API parity)."""

    def decorator(func: Callable):
        import functools
        import inspect

        sig_params = list(inspect.signature(func).parameters.keys())

        @functools.wraps(func)
        def wrapper(*tables: Table) -> Table:
            node = _PandasTransformNode(
                [t._node for t in tables], func, output_schema
            )
            if output_universe is None:
                uni = Universe()
            else:
                idx = (
                    output_universe
                    if isinstance(output_universe, int)
                    else sig_params.index(output_universe)
                )
                uni = tables[idx]._universe
            return Table._from_node(
                node, dict(output_schema.dtypes()), uni
            )

        return wrapper

    return decorator
