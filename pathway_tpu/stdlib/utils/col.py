"""Column manipulation helpers (reference: stdlib/utils/col.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table


def unpack_col(column, *unpacked_columns: str, schema: Any = None) -> Table:
    """Unpack a tuple column into named columns."""
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    if schema is not None:
        names = list(schema.column_names())
    else:
        names = [
            c if isinstance(c, str) else c.name for c in unpacked_columns
        ]
    exprs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**exprs)


def multiapply_all_rows(*args, **kwargs):
    raise NotImplementedError


def apply_all_rows(*args, **kwargs):
    raise NotImplementedError


def groupby_reduce_majority(column, value_column):
    import pathway_tpu as pw

    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    return table.groupby(column).reduce(
        column, majority=pw.reducers.any(value_column)
    )


def flatten_column(column, origin_id: str | None = "origin_id"):
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    flat = table.flatten(column)
    return flat
