"""Column manipulation helpers (reference: stdlib/utils/col.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table


def unpack_col(column, *unpacked_columns: str, schema: Any = None) -> Table:
    """Unpack a tuple column into named columns."""
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    if schema is not None:
        names = list(schema.column_names())
    else:
        names = [
            c if isinstance(c, str) else c.name for c in unpacked_columns
        ]
    exprs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**exprs)


def multiapply_all_rows(
    *cols: Any,
    fun: Any,
    result_col_names: Any,
) -> Table:
    """Apply ``fun`` to whole columns at once: it receives one list per
    input column (aligned by row) and returns one list per output column
    (reference: stdlib/utils/col.py:194). The result table shares the
    input universe."""
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.schema import schema_from_types
    from pathway_tpu.stdlib.utils.pandas_transformer import (
        pandas_transformer,
    )

    table = cols[0].table
    in_names = [f"_c{i}" for i in range(len(cols))]
    sel = table.select(**dict(zip(in_names, cols)))
    out_names = [
        c if isinstance(c, str) else c.name for c in result_col_names
    ]
    out_schema = schema_from_types(**{n: dt.ANY for n in out_names})

    @pandas_transformer(output_schema=out_schema, output_universe=0)
    def inner(df):
        import pandas as pd

        results = fun(*[df[n].tolist() for n in in_names])
        return pd.DataFrame(
            dict(zip(out_names, results)), index=df.index
        )

    return inner(sel)


def apply_all_rows(
    *cols: Any, fun: Any, result_col_name: Any
) -> Table:
    """Single-output variant of multiapply_all_rows (reference:
    stdlib/utils/col.py:241)."""
    return multiapply_all_rows(
        *cols,
        fun=lambda *lists: (fun(*lists),),
        result_col_names=[result_col_name],
    )


def groupby_reduce_majority(column, value_column):
    """Per group, the MOST FREQUENT value (a real majority vote — count
    per (group, value), then argmax; reference: col.py
    groupby_reduce_majority)."""
    import pathway_tpu as pw

    table = column.table
    name = column.name
    if name == "majority":
        raise ValueError(
            "groupby_reduce_majority: the grouping column cannot be named "
            "'majority' (it collides with the result column)"
        )
    sel = table.select(_g=column, _v=value_column)
    counted = sel.groupby(sel._g, sel._v).reduce(
        sel._g, sel._v, _c=pw.reducers.count()
    )
    return counted.groupby(counted._g).reduce(
        **{name: counted._g},
        majority=pw.reducers.argmax(counted._c, counted._v),
    )


def flatten_column(column, origin_id: str | None = "origin_id"):
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    flat = table.flatten(column)
    return flat
