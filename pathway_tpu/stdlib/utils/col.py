"""Column manipulation helpers (reference: stdlib/utils/col.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table


def unpack_col(column, *unpacked_columns: str, schema: Any = None) -> Table:
    """Unpack a tuple column into named columns."""
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    if schema is not None:
        names = list(schema.column_names())
    else:
        names = [
            c if isinstance(c, str) else c.name for c in unpacked_columns
        ]
    exprs = {name: column[i] for i, name in enumerate(names)}
    return table.select(**exprs)


def multiapply_all_rows(
    *cols: Any,
    fun: Any,
    result_col_names: Any,
) -> Table:
    """Apply ``fun`` to whole columns at once: it receives one list per
    input column (aligned by row) and returns one list per output column
    (reference: stdlib/utils/col.py:194). The result table shares the
    input universe."""
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.schema import schema_from_types
    from pathway_tpu.stdlib.utils.pandas_transformer import (
        pandas_transformer,
    )

    table = cols[0].table
    in_names = [f"_c{i}" for i in range(len(cols))]
    sel = table.select(**dict(zip(in_names, cols)))
    out_names = [
        c if isinstance(c, str) else c.name for c in result_col_names
    ]
    out_schema = schema_from_types(**{n: dt.ANY for n in out_names})

    @pandas_transformer(output_schema=out_schema, output_universe=0)
    def inner(df):
        import pandas as pd

        results = fun(*[df[n].tolist() for n in in_names])
        return pd.DataFrame(
            dict(zip(out_names, results)), index=df.index
        )

    return inner(sel)


def apply_all_rows(
    *cols: Any, fun: Any, result_col_name: Any
) -> Table:
    """Single-output variant of multiapply_all_rows (reference:
    stdlib/utils/col.py:241)."""
    return multiapply_all_rows(
        *cols,
        fun=lambda *lists: (fun(*lists),),
        result_col_names=[result_col_name],
    )


def groupby_reduce_majority(column, value_column):
    """Per group, the MOST FREQUENT value (a real majority vote — count
    per (group, value), then argmax; reference: col.py
    groupby_reduce_majority)."""
    import pathway_tpu as pw

    table = column.table
    name = column.name
    if name == "majority":
        raise ValueError(
            "groupby_reduce_majority: the grouping column cannot be named "
            "'majority' (it collides with the result column)"
        )
    sel = table.select(_g=column, _v=value_column)
    counted = sel.groupby(sel._g, sel._v).reduce(
        sel._g, sel._v, _c=pw.reducers.count()
    )
    return counted.groupby(counted._g).reduce(
        **{name: counted._g},
        majority=pw.reducers.argmax(counted._c, counted._v),
    )


def flatten_column(column, origin_id: str | None = "origin_id"):
    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None
    flat = table.flatten(column)
    return flat


def unpack_col_dict(column, schema) -> Table:
    """Unpack a Json-object column into typed columns per ``schema``
    (reference: stdlib/utils/col.py:97-188). Non-optional target dtypes
    unwrap (a JSON null raises at runtime); optional ones map null→None.
    Datetimes round-trip via nanosecond ISO strings, durations via
    nanosecond ints (the Json serialization format)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import dtype as dt

    table = None
    for ref in column._dependencies():
        table = ref.table
        break
    assert table is not None

    dtypes = {
        name: schema.__columns__[name].dtype for name in schema.column_names()
    }

    def convert(name, col):
        target = dtypes[name]
        inner = target.strip_optional()
        is_opt = target.is_optional()

        def optional(col, op):
            if is_opt:
                return pw.if_else(col == pw.Json.NULL, None, op(col))
            return op(col)

        if inner == dt.JSON:
            result = col
        elif inner == dt.BOOL:
            result = col.as_bool()
        elif inner == dt.FLOAT:
            result = col.as_float()
        elif inner == dt.INT:
            result = col.as_int()
        elif inner == dt.STR:
            result = col.as_str()
        elif inner == dt.DATE_TIME_NAIVE:
            result = optional(
                col,
                lambda c: pw.unwrap(c.as_str()).dt.strptime(
                    "%Y-%m-%dT%H:%M:%S.%f"
                ),
            )
        elif inner == dt.DATE_TIME_UTC:
            result = optional(
                col,
                lambda c: pw.unwrap(c.as_str()).dt.strptime(
                    "%Y-%m-%dT%H:%M:%S.%f%z"
                ),
            )
        elif inner == dt.DURATION:
            result = optional(
                col, lambda c: pw.unwrap(c.as_int()).dt.to_duration("ns")
            )
        else:
            raise TypeError(
                f"Unsupported conversion from pw.Json to {target.typehint}"
            )
        return result if is_opt else pw.unwrap(result)

    kw = {
        name: convert(name, column.get(name)) for name in schema.column_names()
    }
    return table.select(**kw).update_types(
        **{n: dtypes[n].typehint for n in dtypes}
    )
