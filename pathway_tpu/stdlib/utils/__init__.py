from pathway_tpu.stdlib.utils import col, filtering
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer

__all__ = ["col", "filtering", "AsyncTransformer"]
