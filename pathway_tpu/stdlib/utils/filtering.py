"""Filtering helpers (reference: stdlib/utils/filtering.py)."""

from __future__ import annotations


def argmax_rows(table, *on, what=None):
    import pathway_tpu as pw

    grouped = table.groupby(*on)
    best = grouped.reduce(argmax_id=pw.reducers.argmax(what))
    return table.having(best.argmax_id)


def argmin_rows(table, *on, what=None):
    import pathway_tpu as pw

    grouped = table.groupby(*on)
    best = grouped.reduce(argmin_id=pw.reducers.argmin(what))
    return table.having(best.argmin_id)
