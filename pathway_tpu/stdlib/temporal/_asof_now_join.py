"""asof_now joins: query-stream joins against the current state of the other
side, never revised by later updates (reference:
python/pathway/stdlib/temporal/_asof_now_join.py; the same as-of-now contract
as the external index query path, src/engine/dataflow.rs:2694)."""

from __future__ import annotations

from pathway_tpu.engine.temporal_nodes import AsofNowJoinNode
from pathway_tpu.internals.joins import JoinMode, JoinResult


class AsofNowJoinResult(JoinResult):
    def _uses_left_id(self) -> bool:
        from pathway_tpu.internals.expression import ColumnReference
        from pathway_tpu.internals.thisclass import left as left_ph

        e = self._id_expr
        return (
            isinstance(e, ColumnReference)
            and e.name == "id"
            and (e.table is self._left or e.table is left_ph)
        )

    def _result_universe(self):
        # id=pw.left.id keys each result row by its query row: LEFT mode
        # covers every query (same universe), INNER a subset (reference:
        # asof_now_join id= contract)
        if self._uses_left_id():
            if self._mode == JoinMode.LEFT:
                return self._left._universe
            return self._left._universe.subset()
        from pathway_tpu.internals.universe import Universe

        return Universe()

    def _build(self):
        lnames = [f"_on{i}" for i in range(len(self._left_on))]
        left_cols = {n: self._left[n] for n in self._left.column_names()}
        left_prep = self._left._build_rowwise(
            {**left_cols, **dict(zip(lnames, self._left_on))}
        )
        right_cols = {n: self._right[n] for n in self._right.column_names()}
        right_prep = self._right._build_rowwise(
            {**right_cols, **dict(zip(lnames, self._right_on))}
        )
        node = AsofNowJoinNode(
            left_prep._node,
            right_prep._node,
            lnames,
            lnames,
            self._mode.value,
            id_from="left" if self._uses_left_id() else None,
        )
        return node, left_prep, right_prep


def asof_now_join(
    self, other, *on, how: JoinMode = JoinMode.INNER, id=None
) -> AsofNowJoinResult:
    """Join each (append-only) row of `self` against the state of `other` at
    the moment the row arrives; results are not updated when `other` changes."""
    if how not in (JoinMode.INNER, JoinMode.LEFT):
        raise ValueError("asof_now_join supports only INNER and LEFT modes")
    return AsofNowJoinResult(self, other, on, how, id)


def asof_now_join_inner(self, other, *on, id=None):
    return asof_now_join(self, other, *on, how=JoinMode.INNER, id=id)


def asof_now_join_left(self, other, *on, id=None):
    return asof_now_join(self, other, *on, how=JoinMode.LEFT, id=id)
