"""Temporal behaviors: delay / cutoff / memory-release for windows and joins
(reference: python/pathway/stdlib/temporal/temporal_behavior.py; engine side
postpone/forget/freeze, src/engine/dataflow/operators/time_column.rs:248,426,509).

On this engine the three mechanisms are the BufferNode / FreezeNode /
ForgetNode microbatch operators (pathway_tpu/engine/nodes.py): each tracks the
maximum time seen on its time column (the operator's own watermark, like the
reference's `current time`) and respectively postpones, drops-late, or
retracts-stale rows against a per-row threshold column.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from pathway_tpu.engine import nodes
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.universe import Universe


class Behavior:
    """Base class of all temporal behaviors."""


@dataclass
class CommonBehavior(Behavior):
    """delay / cutoff / keep_results configuration of a temporal operator."""

    delay: Any | None
    cutoff: Any | None
    keep_results: bool


def common_behavior(
    delay: Any | None = None,
    cutoff: Any | None = None,
    keep_results: bool = True,
) -> CommonBehavior:
    """For windows: ``delay`` postpones a window's first output until the
    operator time passes window_start + delay; ``cutoff`` stops updating (and
    drops late data for) windows ending before max_time - cutoff;
    ``keep_results=False`` additionally retracts results of such closed
    windows. For interval/asof joins the same thresholds apply to each input
    record's own time."""
    assert not (cutoff is None and not keep_results)
    return CommonBehavior(delay, cutoff, keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any | None


def exactly_once_behavior(shift: Any | None = None) -> ExactlyOnceBehavior:
    """Each window produces exactly one output, `shift` after the window
    closes; late data is dropped."""
    return ExactlyOnceBehavior(shift)


# ---------------------------------------------------------------------------
# Engine glue: wrap a table in buffer/freeze/forget nodes.


def _temporal_table(table, node_cls, threshold_expr, time_expr, **kw):
    """Build `node_cls(prep, _pw_thr, _pw_cur)` over `table` and return a
    Table with the original columns."""
    from pathway_tpu.internals.table import Table

    cols = {n: table[n] for n in table.column_names()}
    prep = table._build_rowwise(
        {**cols, "_pw_thr": threshold_expr, "_pw_cur": time_expr}
    )
    node = node_cls(prep._node, "_pw_thr", "_pw_cur", **kw)
    out = Table._from_node(
        node,
        {n: prep._schema[n].dtype for n in prep.column_names()},
        Universe(),
    )
    return out.without("_pw_thr", "_pw_cur")


def _shifted(time_ref, delta):
    """time + delta as an expression; delta may be an int/float/timedelta."""
    if delta is None:
        return time_ref
    return apply_with_type(lambda t: None if t is None else t + delta, dt.ANY, time_ref)


def apply_behavior(
    table,
    time_col: str,
    start_col: str,
    end_col: str,
    behavior: Behavior | None,
):
    """Apply a window behavior to the flattened (row, window) table.

    time_col/start_col/end_col name columns of `table` holding each row's
    event time and its window's [start, end). Column references are re-taken
    from the current table at every wrapping step so chained behavior nodes
    stay single-input."""
    if behavior is None:
        return table
    if isinstance(behavior, ExactlyOnceBehavior):
        shift = behavior.shift
        # drop anything arriving after the window already fired, then hold
        # everything until the window closes -> single emission per window
        table = _temporal_table(
            table, nodes.FreezeNode, _shifted(table[end_col], shift),
            table[time_col],
        )
        table = _temporal_table(
            table, nodes.BufferNode, _shifted(table[end_col], shift),
            table[time_col],
        )
        return table
    assert isinstance(behavior, CommonBehavior)
    if behavior.cutoff is not None:
        table = _temporal_table(
            table, nodes.FreezeNode,
            _shifted(table[end_col], behavior.cutoff), table[time_col],
        )
        if not behavior.keep_results:
            table = _temporal_table(
                table, nodes.ForgetNode,
                _shifted(table[end_col], behavior.cutoff), table[time_col],
            )
    if behavior.delay is not None:
        table = _temporal_table(
            table, nodes.BufferNode,
            _shifted(table[start_col], behavior.delay), table[time_col],
        )
    return table


def apply_behavior_to_side(table, time_col: str, behavior: Behavior | None):
    """Behavior on one input of an interval/asof join: thresholds are keyed to
    each record's own time (reference semantics: delay the record, ignore
    too-old records)."""
    if behavior is None:
        return table
    if isinstance(behavior, ExactlyOnceBehavior):
        raise TypeError(
            "exactly_once_behavior applies to windows, not temporal joins"
        )
    assert isinstance(behavior, CommonBehavior)
    if behavior.cutoff is not None:
        table = _temporal_table(
            table, nodes.FreezeNode,
            _shifted(table[time_col], behavior.cutoff), table[time_col],
        )
        if not behavior.keep_results:
            table = _temporal_table(
                table, nodes.ForgetNode,
                _shifted(table[time_col], behavior.cutoff), table[time_col],
            )
    if behavior.delay is not None:
        table = _temporal_table(
            table, nodes.BufferNode,
            _shifted(table[time_col], behavior.delay), table[time_col],
        )
    return table
