"""As-of joins (reference: python/pathway/stdlib/temporal/_asof_join.py —
there built on sorted prev/next pointer groups; here a dedicated incremental
AsofJoinNode that restates touched equality-groups per tick)."""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.engine.temporal_nodes import AsofJoinNode
from pathway_tpu.internals.expression import (
    CoalesceExpression,
    ColumnReference,
)
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import desugar
from pathway_tpu.internals.thisclass import (
    left as left_ph,
    right as right_ph,
    this as this_ph,
)
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    apply_behavior_to_side,
)


class Direction(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


class AsofJoinResult(JoinResult):
    """Lazy asof join result; select() like a regular join. `defaults` maps a
    source column reference to the value used when the row has no match."""

    def __init__(
        self,
        left,
        right,
        left_time,
        right_time,
        on,
        mode: JoinMode,
        defaults: dict[ColumnReference, Any],
        direction: Direction,
        behavior: Behavior | None = None,
    ):
        super().__init__(left, right, on, mode)
        self._left_time = desugar(left_time, {left_ph: left, this_ph: left})
        self._right_time = desugar(
            right_time, {right_ph: right, this_ph: right}
        )
        self._defaults = {
            (ref.table, ref.name): v for ref, v in (defaults or {}).items()
        }
        self._direction = direction
        self._behavior = behavior

    def _build(self):
        lnames = [f"_on{i}" for i in range(len(self._left_on))]
        left_cols = {n: self._left[n] for n in self._left.column_names()}
        left_prep = self._left._build_rowwise(
            {
                **left_cols,
                **dict(zip(lnames, self._left_on)),
                "_pw_t": self._left_time,
            }
        )
        right_cols = {n: self._right[n] for n in self._right.column_names()}
        right_prep = self._right._build_rowwise(
            {
                **right_cols,
                **dict(zip(lnames, self._right_on)),
                "_pw_t": self._right_time,
            }
        )
        left_prep = apply_behavior_to_side(left_prep, "_pw_t", self._behavior)
        right_prep = apply_behavior_to_side(
            right_prep, "_pw_t", self._behavior
        )
        node = AsofJoinNode(
            left_prep._node,
            right_prep._node,
            lnames,
            lnames,
            "_pw_t",
            "_pw_t",
            self._direction.value,
            self._mode.value,
        )
        return node, left_prep, right_prep

    def _make_sub(self, joined):
        base = super()._make_sub(joined)
        defaults = self._defaults

        def sub(ref: ColumnReference):
            out = base(ref)
            tbl = ref.table
            if tbl is left_ph:
                tbl = self._left
            elif tbl is right_ph:
                tbl = self._right
            key = (tbl, ref.name)
            if key in defaults and out is not None:
                return CoalesceExpression(out, defaults[key])
            return out

        return sub


def asof_join(
    self,
    other,
    self_time,
    other_time,
    *on,
    how: JoinMode = JoinMode.LEFT,
    defaults: dict[ColumnReference, Any] | None = None,
    direction: Direction = Direction.BACKWARD,
    behavior: Behavior | None = None,
) -> AsofJoinResult:
    """For every row, find the single best matching row of the other side by
    time (per `direction`), within groups given by `on` equalities."""
    if how not in (JoinMode.LEFT, JoinMode.RIGHT, JoinMode.OUTER):
        raise ValueError(
            "asof_join supports only LEFT, RIGHT and OUTER modes"
        )
    return AsofJoinResult(
        self, other, self_time, other_time, on, how, defaults or {},
        direction, behavior,
    )


def asof_join_left(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.LEFT,
        defaults=defaults, direction=direction, behavior=behavior,
    )


def asof_join_right(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.RIGHT,
        defaults=defaults, direction=direction, behavior=behavior,
    )


def asof_join_outer(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.OUTER,
        defaults=defaults, direction=direction, behavior=behavior,
    )
