"""As-of joins (reference: python/pathway/stdlib/temporal/_asof_join.py —
there built on sorted prev/next pointer groups; here a dedicated incremental
AsofJoinNode that restates touched equality-groups per tick)."""

from __future__ import annotations

import enum
from typing import Any

from pathway_tpu.engine.temporal_nodes import AsofJoinNode
from pathway_tpu.internals.expression import (
    CoalesceExpression,
    ColumnReference,
)
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import desugar
from pathway_tpu.internals.thisclass import (
    ThisPlaceholder,
    left as left_ph,
    right as right_ph,
    this as this_ph,
)
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    apply_behavior_to_side,
)


class Direction(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


class AsofJoinResult(JoinResult):
    """Lazy asof join result; select() like a regular join. `defaults` maps a
    source column reference to the value used when the row has no match."""

    def __init__(
        self,
        left,
        right,
        left_time,
        right_time,
        on,
        mode: JoinMode,
        defaults: dict[ColumnReference, Any],
        direction: Direction,
        behavior: Behavior | None = None,
    ):
        super().__init__(left, right, on, mode)
        self._left_time = desugar(left_time, {left_ph: left, this_ph: left})
        self._right_time = desugar(
            right_time, {right_ph: right, this_ph: right}
        )
        self._defaults = {
            (ref.table, ref.name): v for ref, v in (defaults or {}).items()
        }
        self._direction = direction
        self._behavior = behavior

    def _build(self):
        lnames = [f"_on{i}" for i in range(len(self._left_on))]
        left_cols = {n: self._left[n] for n in self._left.column_names()}
        left_prep = self._left._build_rowwise(
            {
                **left_cols,
                **dict(zip(lnames, self._left_on)),
                "_pw_t": self._left_time,
            }
        )
        right_cols = {n: self._right[n] for n in self._right.column_names()}
        right_prep = self._right._build_rowwise(
            {
                **right_cols,
                **dict(zip(lnames, self._right_on)),
                "_pw_t": self._right_time,
            }
        )
        left_prep = apply_behavior_to_side(left_prep, "_pw_t", self._behavior)
        right_prep = apply_behavior_to_side(
            right_prep, "_pw_t", self._behavior
        )
        node = AsofJoinNode(
            left_prep._node,
            right_prep._node,
            lnames,
            lnames,
            "_pw_t",
            "_pw_t",
            self._direction.value,
            self._mode.value,
        )
        return node, left_prep, right_prep

    def _make_sub(self, joined):
        base = super()._make_sub(joined)
        defaults = self._defaults
        n_on = len(self._left_on)

        def sub(ref: ColumnReference):
            tbl = ref.table
            # synthetic result columns (reference: the asof merge result
            # exposes `t` — the perspective row's own time — and
            # `instance` — the equated join-key value — via pw.this,
            # SHADOWING same-named source columns). pw.left / pw.right are
            # ThisPlaceholders too: only bare pw.this gets the synthetics.
            if (
                isinstance(tbl, ThisPlaceholder)
                and tbl is not left_ph
                and tbl is not right_ph
            ):
                if ref.name == "t":
                    return ColumnReference(joined, "_pw_self_t")
                if ref.name == "side":
                    return ColumnReference(joined, "_pw_side")
                if ref.name == "instance":
                    conds = [
                        CoalesceExpression(
                            ColumnReference(joined, f"l._on{i}"),
                            ColumnReference(joined, f"r._on{i}"),
                        )
                        for i in range(n_on)
                    ]
                    if not conds:
                        from pathway_tpu.internals.expression import (
                            ColumnConstExpression,
                        )

                        return ColumnConstExpression(None)
                    if len(conds) == 1:
                        return conds[0]
                    from pathway_tpu.internals.common import make_tuple

                    return make_tuple(*conds)
            out = base(ref)
            if tbl is left_ph:
                tbl = self._left
            elif tbl is right_ph:
                tbl = self._right
            key = (tbl, ref.name)
            if key in defaults and out is not None:
                return CoalesceExpression(out, defaults[key])
            return out

        return sub


def asof_join(
    self,
    other,
    self_time,
    other_time,
    *on,
    how: JoinMode = JoinMode.LEFT,
    defaults: dict[ColumnReference, Any] | None = None,
    direction: Direction = Direction.BACKWARD,
    behavior: Behavior | None = None,
    left_instance: ColumnReference | None = None,
    right_instance: ColumnReference | None = None,
) -> AsofJoinResult:
    """For every row, find the single best matching row of the other side by
    time (per `direction`), within groups given by `on` equalities (and the
    optional left_instance == right_instance pair)."""
    if how not in (JoinMode.LEFT, JoinMode.RIGHT, JoinMode.OUTER):
        raise ValueError(
            "asof_join supports only LEFT, RIGHT and OUTER modes"
        )
    if (left_instance is None) != (right_instance is None):
        raise ValueError(
            "asof_join requires both left_instance and right_instance, "
            "or neither"
        )
    if left_instance is not None:
        on = (*on, left_instance == right_instance)
    _validate_asof_join_types(self, other, self_time, other_time, on)
    return AsofJoinResult(
        self, other, self_time, other_time, on, how, defaults or {},
        direction, behavior,
    )


def _validate_asof_join_types(left, right, self_time, other_time, on) -> None:
    """Build-time validation (reference: asof_join check_joint_types over
    eval_type — message names t_left / t_right)."""
    from pathway_tpu.stdlib.temporal.utils import (
        check_joint_kinds,
        expr_kind,
        validate_join_condition_types,
    )

    def kind_of(table, expr):
        e = desugar(expr, {left_ph: left, right_ph: right, this_ph: table})
        return expr_kind(table, e)

    check_joint_kinds(
        {
            "t_left": (kind_of(left, self_time), "time"),
            "t_right": (kind_of(right, other_time), "time"),
        }
    )
    tmp = JoinResult(left, right, on, JoinMode.INNER)
    validate_join_condition_types(left, right, tmp._left_on, tmp._right_on)


def asof_join_left(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
    left_instance=None, right_instance=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.LEFT,
        defaults=defaults, direction=direction, behavior=behavior,
        left_instance=left_instance, right_instance=right_instance,
    )


def asof_join_right(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
    left_instance=None, right_instance=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.RIGHT,
        defaults=defaults, direction=direction, behavior=behavior,
        left_instance=left_instance, right_instance=right_instance,
    )


def asof_join_outer(
    self, other, self_time, other_time, *on,
    defaults=None, direction=Direction.BACKWARD, behavior=None,
    left_instance=None, right_instance=None,
):
    return asof_join(
        self, other, self_time, other_time, *on, how=JoinMode.OUTER,
        defaults=defaults, direction=direction, behavior=behavior,
        left_instance=left_instance, right_instance=right_instance,
    )
