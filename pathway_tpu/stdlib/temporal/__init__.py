import importlib

__all__ = [
    "Interval",
    "IntervalJoinResult",
    "WindowJoinResult",
    "AsofJoinResult",
    "AsofNowJoinResult",
    "windowby",
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "Window",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
    "common_behavior",
    "exactly_once_behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "Direction",
    "utils",
    "utc_now",
    "inactivity_detection",
    "TimestampSchema",
]

_locations = {
    "Interval": "_interval_join",
    "IntervalJoinResult": "_interval_join",
    "WindowJoinResult": "_window_join",
    "AsofJoinResult": "_asof_join",
    "AsofNowJoinResult": "_asof_now_join",
    "windowby": "_window",
    "tumbling": "_window",
    "sliding": "_window",
    "session": "_window",
    "intervals_over": "_window",
    "Window": "_window",
    "interval": "_interval_join",
    "interval_join": "_interval_join",
    "interval_join_inner": "_interval_join",
    "interval_join_left": "_interval_join",
    "interval_join_right": "_interval_join",
    "interval_join_outer": "_interval_join",
    "window_join": "_window_join",
    "window_join_inner": "_window_join",
    "window_join_left": "_window_join",
    "window_join_right": "_window_join",
    "window_join_outer": "_window_join",
    "asof_join": "_asof_join",
    "asof_join_left": "_asof_join",
    "asof_join_right": "_asof_join",
    "asof_join_outer": "_asof_join",
    "asof_now_join": "_asof_now_join",
    "asof_now_join_inner": "_asof_now_join",
    "asof_now_join_left": "_asof_now_join",
    "common_behavior": "temporal_behavior",
    "exactly_once_behavior": "temporal_behavior",
    "CommonBehavior": "temporal_behavior",
    "ExactlyOnceBehavior": "temporal_behavior",
    "Direction": "_asof_join",
}


def __getattr__(name: str):
    if name in ("utils", "time_utils"):
        mod = importlib.import_module(f"pathway_tpu.stdlib.temporal.{name}")
        globals()[name] = mod
        return mod
    if name in ("utc_now", "inactivity_detection", "TimestampSchema"):
        mod = importlib.import_module(
            "pathway_tpu.stdlib.temporal.time_utils"
        )
        obj = getattr(mod, name)
        globals()[name] = obj
        return obj
    if name in _locations:
        mod = importlib.import_module(
            f"pathway_tpu.stdlib.temporal.{_locations[name]}"
        )
        obj = getattr(mod, name)
        globals()[name] = obj
        return obj
    raise AttributeError(name)
