"""Interval joins (reference: python/pathway/stdlib/temporal/_interval_join.py
— there desugared into bucketed equijoins over differential collections; here
a dedicated incremental IntervalJoinNode on the microbatch engine)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from pathway_tpu.engine.temporal_nodes import IntervalJoinNode
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import desugar
from pathway_tpu.internals.thisclass import (
    left as left_ph,
    right as right_ph,
    this as this_ph,
)
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    apply_behavior_to_side,
)


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    """The allowed difference `other_time - self_time` of matching rows.
    Validation happens at the JOIN, not here: mixed bound kinds get the
    reference's Arguments-have-to-be-of-types message, and lower > upper
    the ValueError — both only once the join is built."""
    return Interval(lower_bound, upper_bound)


def _validate_interval_join_types(
    left, right, left_time, right_time, interval, left_on, right_on
) -> None:
    """Build-time validation (reference: interval_join check_joint_types
    over eval_type + join-condition typing)."""
    from pathway_tpu.stdlib.temporal.utils import (
        check_joint_kinds,
        expr_kind,
        validate_join_condition_types,
        value_kind,
    )

    check_joint_kinds(
        {
            "self_time_expression": (expr_kind(left, left_time), "time"),
            "other_time_expression": (expr_kind(right, right_time), "time"),
            "lower_bound": (value_kind(interval.lower_bound), "interval"),
            "upper_bound": (value_kind(interval.upper_bound), "interval"),
        }
    )
    try:
        bad = interval.lower_bound > interval.upper_bound
    except TypeError:  # unreachable: check_joint_kinds already raised
        bad = False
    if bad:
        raise ValueError(
            "interval lower_bound has to be less than or equal to upper_bound"
        )
    validate_join_condition_types(left, right, left_on, right_on)


class IntervalJoinResult(JoinResult):
    """Lazy interval join; `.select(...)` with pw.left / pw.right / pw.this
    materializes, like a regular join."""

    def __init__(
        self,
        left,
        right,
        left_time,
        right_time,
        interval: Interval,
        on,
        mode: JoinMode,
        behavior: Behavior | None = None,
    ):
        super().__init__(left, right, on, mode)
        self._left_time = desugar(left_time, {left_ph: left, this_ph: left})
        self._right_time = desugar(
            right_time, {right_ph: right, this_ph: right}
        )
        self._interval = interval
        self._behavior = behavior
        _validate_interval_join_types(
            left, right, self._left_time, self._right_time, interval,
            self._left_on, self._right_on,
        )

    def _build(self):
        lnames = [f"_on{i}" for i in range(len(self._left_on))]
        left_cols = {n: self._left[n] for n in self._left.column_names()}
        left_prep = self._left._build_rowwise(
            {
                **left_cols,
                **dict(zip(lnames, self._left_on)),
                "_pw_t": self._left_time,
            }
        )
        right_cols = {n: self._right[n] for n in self._right.column_names()}
        right_prep = self._right._build_rowwise(
            {
                **right_cols,
                **dict(zip(lnames, self._right_on)),
                "_pw_t": self._right_time,
            }
        )
        left_prep = apply_behavior_to_side(left_prep, "_pw_t", self._behavior)
        right_prep = apply_behavior_to_side(
            right_prep, "_pw_t", self._behavior
        )
        node = IntervalJoinNode(
            left_prep._node,
            right_prep._node,
            lnames,
            lnames,
            "_pw_t",
            "_pw_t",
            self._interval.lower_bound,
            self._interval.upper_bound,
            self._mode.value,
        )
        return node, left_prep, right_prep


def interval_join(
    self,
    other,
    self_time,
    other_time,
    interval: Interval,
    *on,
    behavior: Behavior | None = None,
    how: JoinMode = JoinMode.INNER,
) -> IntervalJoinResult:
    """Join rows whose time difference `other_time - self_time` lies within
    `interval`, subject to equality conditions `on`."""
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, how, behavior
    )


def interval_join_inner(
    self, other, self_time, other_time, interval, *on, behavior=None
):
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, JoinMode.INNER,
        behavior,
    )


def interval_join_left(
    self, other, self_time, other_time, interval, *on, behavior=None
):
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, JoinMode.LEFT,
        behavior,
    )


def interval_join_right(
    self, other, self_time, other_time, interval, *on, behavior=None
):
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, JoinMode.RIGHT,
        behavior,
    )


def interval_join_outer(
    self, other, self_time, other_time, interval, *on, behavior=None
):
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, JoinMode.OUTER,
        behavior,
    )
