"""Wall-clock helper streams: utc_now + inactivity detection
(reference: python/pathway/stdlib/temporal/time_utils.py)."""

from __future__ import annotations

import datetime
import time
from functools import cache

from pathway_tpu import io
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.datetime_types import DateTimeUtc


class TimestampSchema(schema_mod.Schema):
    timestamp_utc: DateTimeUtc


class TimestampSubject(io.python.ConnectorSubject):
    def __init__(self, refresh_rate: datetime.timedelta) -> None:
        super().__init__()
        self._refresh_rate = refresh_rate

    def run(self) -> None:
        while not getattr(self, "_stop_requested", False):
            now_utc = DateTimeUtc.from_datetime(
                datetime.datetime.now(datetime.timezone.utc)
            )
            self.next(timestamp_utc=now_utc)
            self.commit()
            time.sleep(self._refresh_rate.total_seconds())


@cache
def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)):
    """A live table with a single stream of current-UTC-timestamp rows,
    refreshed every `refresh_rate`."""
    return io.python.read(
        TimestampSubject(refresh_rate=refresh_rate),
        schema=TimestampSchema,
    )


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance=None,
):
    """Detect periods with no events: returns `(inactivities,
    resumed_activities)` — `inactive_t` marks the last timestamp before an
    inactivity longer than `allowed_inactivity_period` (per `instance` if
    given), `resumed_t` the first event after it (reference:
    stdlib/temporal/time_utils.py inactivity_detection)."""
    import pathway_tpu as pw

    events_t = event_time_column.table.select(
        t=event_time_column, instance=instance
    )

    now_t = utc_now(refresh_rate=refresh_rate)
    latest_t = (
        events_t.groupby(pw.this.instance)
        .reduce(pw.this.instance, latest_t=pw.reducers.max(pw.this.t))
        .filter(
            pw.this.latest_t
            > DateTimeUtc.from_datetime(
                datetime.datetime.now(datetime.timezone.utc)
            )
        )  # filter to avoid alerts during backfilling
    )
    inactivities = (
        now_t.asof_now_join(latest_t)
        .select(pw.left.timestamp_utc, pw.right.instance, pw.right.latest_t)
        .filter(
            pw.this.latest_t + allowed_inactivity_period
            < pw.this.timestamp_utc
        )
        .groupby(pw.this.latest_t, pw.this.instance)
        .reduce(pw.this.latest_t, pw.this.instance)
        .select(instance=pw.this.instance, inactive_t=pw.this.latest_t)
    )

    latest_inactivity = inactivities.groupby(pw.this.instance).reduce(
        pw.this.instance, inactive_t=pw.reducers.latest(pw.this.inactive_t)
    )
    resumed_activities = (
        events_t.asof_now_join(
            latest_inactivity, events_t.instance == latest_inactivity.instance
        )
        .select(pw.left.t, pw.left.instance, pw.right.inactive_t)
        .groupby(pw.this.inactive_t, pw.this.instance)
        .reduce(pw.this.instance, resumed_t=pw.reducers.min(pw.this.t))
    )
    if instance is None:
        inactivities = inactivities.without("instance")
        resumed_activities = resumed_activities.without("instance")
    return inactivities, resumed_activities
