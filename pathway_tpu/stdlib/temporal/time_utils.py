"""Wall-clock helper streams: utc_now + inactivity detection
(reference: python/pathway/stdlib/temporal/time_utils.py)."""

from __future__ import annotations

import datetime
import time
from functools import cache

from pathway_tpu import io
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.datetime_types import DateTimeUtc


class TimestampSchema(schema_mod.Schema):
    timestamp_utc: DateTimeUtc


class TimestampSubject(io.python.ConnectorSubject):
    def __init__(self, refresh_rate: datetime.timedelta) -> None:
        super().__init__()
        self._refresh_rate = refresh_rate

    def run(self) -> None:
        while not getattr(self, "_stop_requested", False):
            now_utc = DateTimeUtc.from_datetime(
                datetime.datetime.now(datetime.timezone.utc)
            )
            self.next(timestamp_utc=now_utc)
            self.commit()
            time.sleep(self._refresh_rate.total_seconds())


@cache
def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)):
    """A live table with a single stream of current-UTC-timestamp rows,
    refreshed every `refresh_rate`."""
    return io.python.read(
        TimestampSubject(refresh_rate=refresh_rate),
        schema=TimestampSchema,
    )


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance=None,
):
    """Detect periods with no events: returns `(inactivities,
    resumed_activities)`. A row lands in `inactivities` when no event arrived
    for `allowed_inactivity_period` (per `instance` if given); a row lands in
    `resumed_activities` at the first event after each inactivity period."""
    import pathway_tpu as pw

    events = event_time_column.table
    now = utc_now(refresh_rate=refresh_rate)

    has_instance = instance is not None
    if has_instance:
        last_event = events.groupby(instance).reduce(
            instance=instance, latest=pw.reducers.max(event_time_column)
        )
    else:
        last_event = events.reduce(
            latest=pw.reducers.max(event_time_column)
        )
    latest_now = now.reduce(now=pw.reducers.max(now.timestamp_utc))

    le = last_event.with_columns(_c=0)
    ln = latest_now.with_columns(_c=0)
    sel = {"latest": pw.left.latest, "now": pw.right.now}
    if has_instance:
        sel["instance"] = pw.left.instance
    combined = le.join(ln, pw.left._c == pw.right._c).select(**sel)
    inactive_sel = {"inactive_since": pw.this.latest}
    if has_instance:
        inactive_sel["instance"] = pw.this.instance
    inactivities = (
        combined.filter(
            pw.apply_with_type(
                lambda latest, now: (
                    latest is not None
                    and now is not None
                    and (now - latest) > allowed_inactivity_period
                ),
                bool,
                combined.latest,
                combined.now,
            )
        )
        .select(**inactive_sel)
        .deduplicate(
            value=pw.this.inactive_since,
            instance=pw.this.instance if has_instance else None,
        )
    )

    ev_sel = {"_pw_t": event_time_column}
    if has_instance:
        ev_sel["_pw_inst"] = instance
    ev = events.select(**ev_sel)
    join_on = (
        (ev._pw_inst == inactivities.instance,) if has_instance else ()
    )
    res_sel = {"_pw_t": ev._pw_t, "_pw_since": inactivities.inactive_since}
    if has_instance:
        res_sel["instance"] = inactivities.instance
    out_sel = {
        "resumed_at": pw.this._pw_t,
        "inactive_since": pw.this._pw_since,
    }
    if has_instance:
        out_sel["instance"] = pw.this.instance
    resumed = (
        ev.asof_now_join(inactivities, *join_on)
        .select(**res_sel)
        .filter(pw.this._pw_t > pw.this._pw_since)
        .deduplicate(
            value=pw.this._pw_since,
            instance=pw.this.instance if has_instance else None,
        )
        .select(**out_sel)
    )
    return inactivities, resumed
