"""Time-type utilities for temporal operators
(reference: python/pathway/stdlib/temporal/utils.py)."""

from __future__ import annotations

import datetime
from typing import Any, Union

from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
)

TimeEventType = Union[int, float, datetime.datetime]
IntervalType = Union[int, float, datetime.timedelta]

_TIME_KINDS = {
    int: "int",
    float: "float",
}


def _kind(value: Any) -> str:
    if isinstance(value, bool):
        return "other"
    if isinstance(value, int):
        return "int"
    if isinstance(value, float):
        return "float"
    if isinstance(value, datetime.datetime):
        return "utc" if value.tzinfo is not None else "naive"
    if isinstance(value, datetime.timedelta):
        return "duration"
    return "other"


def check_joint_types(parameters: dict[str, tuple[Any, str]]) -> None:
    """Validate that time/interval values are of compatible kinds, e.g. a
    datetime time column with timedelta bounds, or int with int."""
    allowed = [
        {"time": "int", "interval": "int"},
        {"time": "float", "interval": "int"},
        {"time": "float", "interval": "float"},
        {"time": "int", "interval": "float"},
        {"time": "naive", "interval": "duration"},
        {"time": "utc", "interval": "duration"},
    ]
    kinds = {name: (_kind(v), role) for name, (v, role) in parameters.items()}
    for combo in allowed:
        if all(combo.get(role) == k for _n, (k, role) in kinds.items()):
            return
    raise TypeError(
        "incompatible time/interval types in temporal operator: "
        + ", ".join(f"{n}={k}" for n, (k, _r) in kinds.items())
    )


def zero_length_interval(time_value: Any):
    """An additive zero matching the type of `time_value`."""
    if isinstance(time_value, datetime.datetime):
        return Duration()
    if isinstance(time_value, float):
        return 0.0
    return 0


__all__ = [
    "TimeEventType",
    "IntervalType",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "check_joint_types",
    "zero_length_interval",
]
