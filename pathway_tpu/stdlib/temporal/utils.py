"""Time-type utilities for temporal operators
(reference: python/pathway/stdlib/temporal/utils.py)."""

from __future__ import annotations

import datetime
from typing import Any, Union

from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
)

TimeEventType = Union[int, float, datetime.datetime]
IntervalType = Union[int, float, datetime.timedelta]

_TIME_KINDS = {
    int: "int",
    float: "float",
}


def _kind(value: Any) -> str:
    if isinstance(value, bool):
        return "other"
    if isinstance(value, int):
        return "int"
    if isinstance(value, float):
        return "float"
    if isinstance(value, datetime.datetime):
        return "utc" if value.tzinfo is not None else "naive"
    if isinstance(value, datetime.timedelta):
        return "duration"
    return "other"


def check_joint_types(parameters: dict[str, tuple[Any, str]]) -> None:
    """Validate that time/interval values are of compatible kinds, e.g. a
    datetime time column with timedelta bounds, or int with int."""
    allowed = [
        {"time": "int", "interval": "int"},
        {"time": "float", "interval": "int"},
        {"time": "float", "interval": "float"},
        {"time": "int", "interval": "float"},
        {"time": "naive", "interval": "duration"},
        {"time": "utc", "interval": "duration"},
    ]
    kinds = {name: (_kind(v), role) for name, (v, role) in parameters.items()}
    for combo in allowed:
        if all(combo.get(role) == k for _n, (k, role) in kinds.items()):
            return
    raise TypeError(
        "incompatible time/interval types in temporal operator: "
        + ", ".join(f"{n}={k}" for n, (k, _r) in kinds.items())
    )


def zero_length_interval(time_value: Any):
    """An additive zero matching the type of `time_value`."""
    if isinstance(time_value, datetime.datetime):
        return Duration()
    if isinstance(time_value, float):
        return 0.0
    return 0


__all__ = [
    "TimeEventType",
    "IntervalType",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "check_joint_types",
    "zero_length_interval",
]


# --- build-time dtype validation (reference: utils.check_joint_types over
# eval_type; error format "Arguments (...) have to be of types ... but are
# of types ...", tests/temporal/test_windows.py test_incorrect_args) ------

_TIME_POSSIBLE = ("int", "float", "naive", "utc")
_INTERVAL_POSSIBLE = ("int", "float", "duration", "duration")
_KIND_REPR = {
    "int": "INT",
    "float": "FLOAT",
    "naive": "DATE_TIME_NAIVE",
    "utc": "DATE_TIME_UTC",
    "duration": "DURATION",
}


def dtype_kind(dtype: Any) -> str | None:
    """Map an engine dtype to a time-kind string, or None when unknown
    (ANY columns skip validation — markdown fixtures stay permissive)."""
    from pathway_tpu.internals import dtype as dt

    strip = getattr(dtype, "strip_optional", None)
    if strip is not None:  # Optional_[x] validates as its inner type
        dtype = strip()
    mapping = {
        dt.INT: "int",
        dt.FLOAT: "float",
        dt.DATE_TIME_NAIVE: "naive",
        dt.DATE_TIME_UTC: "utc",
        dt.DURATION: "duration",
    }
    if dtype in mapping:
        return mapping[dtype]
    if dtype == dt.ANY:
        return None
    return str(dtype)  # e.g. 'str' — always fails, named in the message


def check_joint_kinds(params: dict[str, tuple[str | None, str]]) -> None:
    """params: name -> (kind, role) with role in {'time', 'interval'}.
    Kinds of None (unknown/ANY) are skipped. All remaining args must fit
    one column of the (time, interval) compatibility table, with int
    acceptable where float is expected."""
    live = {n: v for n, v in params.items() if v[0] is not None}
    if not live:
        return

    def fits(kind: str, expected: str) -> bool:
        return kind == expected or (kind == "int" and expected == "float")

    def expected_of(role: str, i: int) -> str:
        return (_TIME_POSSIBLE if role == "time" else _INTERVAL_POSSIBLE)[i]

    for i in range(len(_TIME_POSSIBLE)):
        if all(fits(k, expected_of(role, i)) for k, role in live.values()):
            return
    def fmt(kinds) -> str:  # reference prints bare names, no quotes
        return "(" + ", ".join(kinds) + ")"

    expected_str = " or ".join(
        fmt(_KIND_REPR[expected_of(role, i)] for _k, role in live.values())
        for i in range(len(_TIME_POSSIBLE))
    )
    actual = fmt(
        _KIND_REPR.get(k, str(k).upper()) for k, _ in live.values()
    )
    raise TypeError(
        f"Arguments ({', '.join(live)}) have to be of types "
        f"{expected_str} but are of types {actual}."
    )


def value_kind(value: Any) -> str | None:
    """_kind for runtime window parameters, None for None."""
    return None if value is None else _kind(value)


def expr_kind(table, expr) -> str | None:
    """Time-kind of an expression over `table` (dtype probe via a throwaway
    rowwise build — the liveness pass prunes it)."""
    prep = table._build_rowwise({"_pw_probe": expr})
    return dtype_kind(prep._schema["_pw_probe"].dtype)


def validate_join_condition_types(left, right, left_on, right_on) -> None:
    """Equi-join conditions must relate compatible dtypes (reference: the
    temporal joins' join-condition typing) — shared by interval, window and
    asof joins."""
    from pathway_tpu.internals import dtype as dt

    for l_e, r_e in zip(left_on, right_on):
        ld = left._build_rowwise({"_pw_probe": l_e})._schema["_pw_probe"].dtype
        rd = (
            right._build_rowwise({"_pw_probe": r_e})
            ._schema["_pw_probe"]
            .dtype
        )
        if ld != dt.ANY and rd != dt.ANY and dt.lub(ld, rd) == dt.ANY:
            raise TypeError(
                f"Cannot join on columns of incompatible types {ld} "
                f"and {rd}."
            )
