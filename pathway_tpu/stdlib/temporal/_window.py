"""Windows: tumbling / sliding / session / intervals_over + windowby
(reference: python/pathway/stdlib/temporal/_window.py — there desugared onto
differential groupbys; here onto the columnar microbatch engine:
window-assignment is a vectorized flatten, sessions are an incremental
SessionAssignNode, intervals_over rides the IntervalJoinNode, and behaviors
are Buffer/Freeze/Forget engine nodes).

Reduce over a windowed table sees the hidden columns ``_pw_window``,
``_pw_window_start``, ``_pw_window_end``, ``_pw_instance`` (and
``_pw_window_location`` for intervals_over), same as the reference.
"""

from __future__ import annotations

import datetime
import math
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.engine.temporal_nodes import IntervalJoinNode, SessionAssignNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.common import apply_with_type, make_tuple
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
)
from pathway_tpu.internals.groupbys import GroupedTable
from pathway_tpu.internals.universe import Universe
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    apply_behavior,
)

_HIDDEN = (
    "_pw_window",
    "_pw_window_start",
    "_pw_window_end",
    "_pw_instance",
    "_pw_window_location",
    "_pw_key",
)


def _instance_col_name(instance, flat) -> str | None:
    """Name of the original instance column when windowby's instance= was a
    plain column still present on the flattened table."""
    if isinstance(instance, ColumnReference) and instance.name in (
        flat.column_names()
    ):
        return instance.name
    return None


def _default_origin(t: Any) -> Any:
    if isinstance(t, datetime.datetime):
        return datetime.datetime(1970, 1, 1, tzinfo=t.tzinfo)
    return 0


class _WindowedGroupedTable(GroupedTable):
    """Warns when processing-time reducers meet data-time windows
    (reference: windowby reduce latest-reducer warning,
    stdlib/temporal/_window.py)."""

    # the groupby this table builds aggregates WINDOWS, not raw groups —
    # the Graph Doctor's unbounded-state rule downgrades it (state grows
    # with open windows; a behavior bounds it fully)
    _pw_windowed = True

    def reduce(self, *args: Any, **kwargs: Any):
        import warnings

        from pathway_tpu.internals.expression import ReducerExpression

        for e in list(args) + list(kwargs.values()):
            name = getattr(
                getattr(e, "_reducer", None), "name", None
            ) if isinstance(e, ReducerExpression) else None
            if name in ("latest", "earliest"):
                warnings.warn(
                    f"{name} reducer uses processing time to choose elements"
                    " while windowby uses data time to assign entries to"
                    " windows. Maybe it is not the behavior you want. To"
                    " choose elements according to their data time, you may"
                    f" use {'max' if name == 'latest' else 'min'} reducer.",
                    stacklevel=2,
                )
        return super().reduce(*args, **kwargs)


def _windowed_grouped(
    flat, *, instance: bool, sort_by: str = "_pw_key", extra_group=None
):
    """GroupedTable over the flattened (row, window) table, grouped by the
    window identity columns. `extra_group` names the ORIGINAL instance
    column when windowby was given a plain column — the reference lets
    reduce() select it directly (it is constant within a window)."""
    grouping = [
        flat._pw_window,
        flat._pw_window_start,
        flat._pw_window_end,
        flat._pw_instance,  # constant None without an instance
    ]
    if extra_group is not None:
        grouping.append(flat[extra_group])
    return _WindowedGroupedTable(flat, grouping, sort_by=flat[sort_by])


class Window(ABC):
    @abstractmethod
    def _apply(self, table, key, behavior, instance):
        ...

    @abstractmethod
    def _join(self, left, right, left_time, right_time, on, mode, behavior):
        ...


# ---------------------------------------------------------------------------
# Sliding / tumbling


@dataclass
class _SlidingWindow(Window):
    hop: Any
    duration: Any
    origin: Any | None
    ratio: int | None = None  # window length = ratio * hop (stable bounds)

    def _assign_fn(self) -> Callable[[Any], tuple]:
        hop, duration, origin0 = self.hop, self.duration, self.origin
        ratio = self.ratio

        def assign(t):
            if t is None:
                return ()
            origin = origin0 if origin0 is not None else _default_origin(t)
            # candidate k range, then STABLE bounds ((k+ratio)*hop computed
            # fresh per window — a ratio-specified window end never drifts
            # from the (k+ratio)-th window start) filtered by actual
            # membership; windows before an explicit origin are dropped —
            # reference: SlidingWindow._window_assignment_function
            last_k = int((t - origin) // hop) + 1
            if ratio is not None:
                first_k = last_k - ratio - 2
            else:
                first_k = last_k - int(duration // hop) - 2
            out = []
            for k in range(first_k, last_k + 1):
                start = k * hop + origin
                if ratio is not None:
                    end = (k + ratio) * hop + origin
                else:
                    end = k * hop + origin + duration
                if start <= t < end and (origin0 is None or start >= origin0):
                    out.append((start, end))
            return tuple(out)

        return assign

    def _flatten(self, table, key, instance):
        """(row, window) table with _pw_* columns."""
        cols = {n: table[n] for n in table.column_names() if n not in _HIDDEN}
        prep_exprs = {**cols, "_pw_key": key}
        has_instance = instance is not None
        if has_instance:
            prep_exprs["_pw_instance"] = instance
        prep = table._build_rowwise(prep_exprs)
        assigned = prep.with_columns(
            _pw_windows=apply_with_type(
                self._assign_fn(), dt.ANY, prep._pw_key
            )
        )
        flat = assigned.flatten(assigned._pw_windows)
        out_exprs = {n: flat[n] for n in cols}
        out_exprs["_pw_key"] = flat._pw_key
        inst_expr = flat._pw_instance if has_instance else None
        out_exprs["_pw_window_start"] = flat._pw_windows[0]
        out_exprs["_pw_window_end"] = flat._pw_windows[1]
        out_exprs["_pw_window"] = make_tuple(
            inst_expr, flat._pw_windows[0], flat._pw_windows[1]
        )
        # _pw_instance is ALWAYS exposed (None without an instance), as in
        # the reference's windowby output schema
        out_exprs["_pw_instance"] = (
            flat._pw_instance if has_instance else None
        )
        return flat.select(**out_exprs), has_instance

    def _apply(self, table, key, behavior, instance):
        flat, has_instance = self._flatten(table, key, instance)
        flat = apply_behavior(
            flat, "_pw_key", "_pw_window_start", "_pw_window_end", behavior
        )
        return _windowed_grouped(
            flat,
            instance=has_instance,
            extra_group=_instance_col_name(instance, flat),
        )

    def _join(self, left, right, left_time, right_time, on, mode, behavior):
        from pathway_tpu.internals.table import desugar
        from pathway_tpu.internals.thisclass import (
            left as left_ph,
            right as right_ph,
            this as this_ph,
        )
        from pathway_tpu.stdlib.temporal._window_join import (
            _window_join_flattened,
        )

        ltime = desugar(left_time, {left_ph: left, this_ph: left})
        rtime = desugar(right_time, {right_ph: right, this_ph: right})
        lflat, _ = self._flatten(left, ltime, None)
        rflat, _ = self._flatten(right, rtime, None)
        lflat = apply_behavior(
            lflat, "_pw_key", "_pw_window_start", "_pw_window_end", behavior
        )
        rflat = apply_behavior(
            rflat, "_pw_key", "_pw_window_start", "_pw_window_end", behavior
        )
        return _window_join_flattened(left, right, lflat, rflat, on, mode)


def tumbling(duration, origin=None) -> Window:
    """Fixed-size non-overlapping windows of `duration`, aligned to
    `origin` (default: 0 / epoch)."""
    _check_window_params(duration, duration, origin)
    w = _SlidingWindow(hop=duration, duration=None, origin=origin, ratio=1)
    w._tumbling = True  # build-time validation names only window.hop
    return w


def _validate_window_types(table, key, window) -> None:
    """Build-time dtype validation of the time column against the window's
    parameters (reference: check_joint_types over eval_type in every
    window's _apply, stdlib/temporal/_window.py)."""
    from pathway_tpu.stdlib.temporal.utils import (
        check_joint_kinds,
        expr_kind,
        value_kind,
    )

    kk = expr_kind(table, key)
    if isinstance(window, _SlidingWindow):
        params = {
            "time_expr": (kk, "time"),
            "window.hop": (value_kind(window.hop), "interval"),
        }
        if not getattr(window, "_tumbling", False) and window.duration is not None:
            params["window.duration"] = (
                value_kind(window.duration),
                "interval",
            )
        params["window.origin"] = (value_kind(window.origin), "time")
        check_joint_kinds(params)
    elif isinstance(window, _SessionWindow):
        check_joint_kinds(
            {
                "time_expr": (kk, "time"),
                "window.max_gap": (value_kind(window.max_gap), "interval"),
            }
        )
    elif isinstance(window, _IntervalsOverWindow):
        check_joint_kinds(
            {
                "time_expr": (kk, "time"),
                "window.lower_bound": (
                    value_kind(window.lower_bound),
                    "interval",
                ),
                "window.upper_bound": (
                    value_kind(window.upper_bound),
                    "interval",
                ),
            }
        )


def _check_window_params(hop, duration, origin):
    from pathway_tpu.stdlib.temporal.utils import _kind

    numeric = {"int", "float"}
    kh, kd = _kind(hop), _kind(duration)
    if not (
        (kh in numeric and kd in numeric)
        or (kh == "duration" and kd == "duration")
    ):
        raise TypeError(
            "window hop and duration must both be numbers or both be "
            f"durations, got {type(hop).__name__} and {type(duration).__name__}"
        )
    if origin is not None:
        ko = _kind(origin)
        if (kh in numeric) != (ko in numeric):
            raise TypeError(
                "window origin must be a number for numeric windows or a "
                f"datetime for duration windows, got {type(origin).__name__}"
            )


def sliding(hop, duration=None, ratio=None, origin=None) -> Window:
    """Windows of `duration` (or hop*ratio) starting every `hop`."""
    if (duration is None) == (ratio is None):
        raise ValueError(
            "exactly one of `duration` or `ratio` should be provided"
        )
    _check_window_params(hop, duration if duration is not None else hop, origin)
    return _SlidingWindow(hop=hop, duration=duration, origin=origin, ratio=ratio)


# ---------------------------------------------------------------------------
# Session


@dataclass
class _SessionWindow(Window):
    predicate: Callable[[Any, Any], bool] | None
    max_gap: Any | None

    def _flatten(self, table, key, instance):
        from pathway_tpu.internals.table import Table

        cols = {n: table[n] for n in table.column_names() if n not in _HIDDEN}
        prep_exprs = {**cols, "_pw_key": key}
        has_instance = instance is not None
        if has_instance:
            prep_exprs["_pw_instance"] = instance
        prep = table._build_rowwise(prep_exprs)
        node = SessionAssignNode(
            prep._node,
            "_pw_key",
            "_pw_instance" if has_instance else None,
            self.predicate,
            self.max_gap,
        )
        sess = Table._from_node(
            node,
            {"_pw_window_start": dt.ANY, "_pw_window_end": dt.ANY},
            prep._universe,
        )
        out_exprs = {n: prep[n] for n in cols}
        out_exprs["_pw_key"] = prep._pw_key
        out_exprs["_pw_window_start"] = sess._pw_window_start
        out_exprs["_pw_window_end"] = sess._pw_window_end
        out_exprs["_pw_window"] = make_tuple(
            prep._pw_instance if has_instance else None,
            sess._pw_window_start,
            sess._pw_window_end,
        )
        out_exprs["_pw_instance"] = (
            prep._pw_instance if has_instance else None
        )
        return prep.select(**out_exprs), has_instance

    def _apply(self, table, key, behavior, instance):
        flat, has_instance = self._flatten(table, key, instance)
        flat = apply_behavior(
            flat, "_pw_key", "_pw_window_start", "_pw_window_end", behavior
        )
        return _windowed_grouped(
            flat,
            instance=has_instance,
            extra_group=_instance_col_name(instance, flat),
        )

    def _join(self, left, right, left_time, right_time, on, mode, behavior):
        from pathway_tpu.stdlib.temporal._window_join import (
            _session_window_join,
        )

        return _session_window_join(
            self, left, right, left_time, right_time, on, mode, behavior
        )


def session(*, predicate=None, max_gap=None) -> Window:
    """Merge adjacent (in time order) rows into one window when
    `predicate(a, b)` holds or `b - a < max_gap`."""
    if (predicate is None) == (max_gap is None):
        raise ValueError(
            "exactly one of [predicate, max_gap] should be provided"
        )
    return _SessionWindow(predicate=predicate, max_gap=max_gap)


# ---------------------------------------------------------------------------
# intervals_over


@dataclass
class _IntervalsOverWindow(Window):
    at: ColumnReference
    lower_bound: Any
    upper_bound: Any
    is_outer: bool

    def _apply(self, table, key, behavior, instance):
        from pathway_tpu.internals.table import Table

        lower, upper = self.lower_bound, self.upper_bound
        at_table = self.at.table
        # distinct probe locations
        probes_tbl = at_table.select(_pw_at=self.at)
        probes_distinct = probes_tbl.groupby(probes_tbl._pw_at).reduce(
            probes_tbl._pw_at
        )

        cols = {n: table[n] for n in table.column_names() if n not in _HIDDEN}
        prep_exprs = {**cols, "_pw_key": key}
        has_instance = instance is not None
        if has_instance:
            prep_exprs["_pw_instance"] = instance
        prep = table._build_rowwise(prep_exprs)

        node = IntervalJoinNode(
            probes_distinct._node,
            prep._node,
            [],
            [],
            "_pw_at",
            "_pw_key",
            lower,
            upper,
            "inner",
        )
        jcols = {}
        for n in probes_distinct.column_names():
            jcols["l." + n] = dt.ANY
        for n in prep.column_names():
            jcols["r." + n] = dt.ANY
        jcols["_left_id"] = dt.Optional_(dt.POINTER)
        jcols["_right_id"] = dt.Optional_(dt.POINTER)
        joined = Table._from_node(node, jcols, Universe())

        out_exprs = {n: joined["r." + n] for n in cols}
        out_exprs["_pw_key"] = joined["r._pw_key"]
        loc = joined["l._pw_at"]
        out_exprs["_pw_window_location"] = loc
        out_exprs["_pw_window_start"] = apply_with_type(
            lambda x: None if x is None else x + lower, dt.ANY, loc
        )
        out_exprs["_pw_window_end"] = apply_with_type(
            lambda x: None if x is None else x + upper, dt.ANY, loc
        )
        inst_expr = joined["r._pw_instance"] if has_instance else None
        out_exprs["_pw_window"] = make_tuple(inst_expr, loc)
        if has_instance:
            out_exprs["_pw_instance"] = joined["r._pw_instance"]
        flat = joined.select(**out_exprs)
        grouping = [
            flat._pw_window,
            flat._pw_window_location,
            flat._pw_window_start,
            flat._pw_window_end,
        ]
        if has_instance:
            grouping.append(flat._pw_instance)
        return _IntervalsOverGrouped(
            flat,
            grouping,
            sort_by=flat._pw_key,
            window=self,
            probes_distinct=probes_distinct,
            has_instance=has_instance,
        )

    def _join(self, left, right, left_time, right_time, on, mode, behavior):
        raise NotImplementedError(
            "window_join does not support intervals_over windows"
        )


class _IntervalsOverGrouped(GroupedTable):
    """GroupedTable for intervals_over: with is_outer=True, probe locations
    with no rows in range still produce an output row with None in every
    non-grouping column (reference: _IntervalsOverWindow, is_outer)."""

    _pw_windowed = True

    def __init__(
        self, table, grouping, *, sort_by, window, probes_distinct, has_instance
    ):
        super().__init__(table, grouping, sort_by=sort_by)
        self._window = window
        self._probes = probes_distinct
        self._has_instance = has_instance

    def reduce(self, *args: Any, **kwargs: Any):
        reduced = super().reduce(*args, **kwargs)
        if not self._window.is_outer or self._has_instance:
            # with instance sharding the empty-window universe is undefined
            # (no instance value to attach) — reference behaves likewise
            return reduced

        # name -> source expr, to figure out which outputs are derivable
        # from the probe location alone
        table = self._table
        out_exprs: dict[str, Any] = {}
        for a in args:
            if isinstance(a, ColumnReference):
                out_exprs[a.name] = table[a.name] if a.table is not table else a
        for n, e in kwargs.items():
            out_exprs[n] = e

        lower, upper = self._window.lower_bound, self._window.upper_bound
        probes = self._probes
        loc = probes._pw_at

        def probe_side_expr(name: str, e: Any):
            if isinstance(e, ColumnReference):
                if e.name == "_pw_window_location":
                    return loc
                if e.name == "_pw_window_start":
                    return apply_with_type(
                        lambda x: x + lower, dt.ANY, loc
                    )
                if e.name == "_pw_window_end":
                    return apply_with_type(
                        lambda x: x + upper, dt.ANY, loc
                    )
                if e.name == "_pw_window":
                    return make_tuple(None, loc)
            return None

        def reducer_null_fill(e: Any):
            """An empty outer window behaves like an outer join's null row:
            COLLECTION reducers materialize that row ((None,) — reference:
            intervals_over is_outer with sorted_tuple), scalar aggregates
            stay None."""
            desc = e._reducer
            if desc.kind not in ("tuple", "sorted_tuple", "ndarray"):
                return None
            from pathway_tpu.engine.reducers import ReducerSpec

            try:
                spec = ReducerSpec(
                    kind=desc.kind,
                    arg_cols=(0,) * max(1, len(e._args)),
                    skip_nones=desc.skip_nones,
                    fn=desc.fn,
                    extra=desc.extra,
                )
                acc = spec.make()
                acc.update((None,) * max(1, len(e._args)), 1, 0, 0)
                return acc.value()
            except Exception:
                return None

        names = list(reduced.column_names())
        empty_exprs = {}
        for n in names:
            src = out_exprs.get(n)
            # grouping-derived outputs get their probe-side value; reducers
            # aggregate over the outer join's null row; anything else
            # touching data columns becomes None
            if isinstance(src, ReducerExpression):
                empty_exprs[n] = reducer_null_fill(src)
            else:
                empty_exprs[n] = (
                    probe_side_expr(n, src) if src is not None else None
                )
        # probes that currently have no matching rows = probes minus the
        # locations present in `reduced`
        reduced_locs = None
        loc_out_name = None
        for n, src in out_exprs.items():
            if (
                isinstance(src, ColumnReference)
                and src.name == "_pw_window_location"
            ):
                loc_out_name = n
                break
        probes_keyed = probes.with_id_from(probes._pw_at)
        # re-keying by the probe value lands on the SAME ids the distinct
        # groupby assigned (both ref_scalar(_pw_at))
        probes_keyed.promise_universe_is_equal_to(probes)
        if loc_out_name is not None:
            reduced_keyed = reduced.with_id_from(reduced[loc_out_name])
        else:
            # user did not select the location — rebuild it from grouping
            with_loc = super().reduce(
                _pw_window_location=table._pw_window_location
            )
            reduced_keyed = with_loc.with_id_from(
                with_loc._pw_window_location
            )
        empty = probes_keyed.difference(reduced_keyed)
        empty_rows = empty.select(**empty_exprs)
        # empty is probes-minus-reduced: provably disjoint from reduced
        reduced.promise_universes_are_disjoint(empty_rows)
        return reduced.concat(empty_rows)


def intervals_over(
    *, at: ColumnReference, lower_bound, upper_bound, is_outer: bool = True
) -> Window:
    """One window per time t in `at`, spanning [t+lower_bound, t+upper_bound];
    `is_outer` keeps empty windows (reducers yield None)."""
    return _IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


# ---------------------------------------------------------------------------
# windowby


def windowby(
    self,
    time_expr,
    *,
    window: Window,
    behavior: Behavior | None = None,
    instance=None,
    shard=None,
) -> GroupedTable:
    """Group `self` by windows over `time_expr`; reduce() then aggregates per
    (window, instance)."""
    if instance is None:
        instance = shard
    key = self._desugar(time_expr)
    inst = self._desugar(instance) if instance is not None else None
    _validate_window_types(self, key, window)
    return window._apply(self, key, behavior, inst)
