"""Window joins: pair rows of two tables that fall into the same window
(reference: python/pathway/stdlib/temporal/_window_join.py). Tumbling/sliding
window joins desugar to window-assignment flattens + a regular equijoin on the
window identity; session window joins compute sessions over the union of both
sides' times per join group, then equijoin on the merged window."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.temporal_nodes import SessionAssignNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.common import make_tuple
from pathway_tpu.internals.expression import (
    CoalesceExpression,
    ColumnReference,
    wrap_expr,
)
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import desugar
from pathway_tpu.internals.thisclass import (
    ThisPlaceholder,
    left as left_ph,
    right as right_ph,
    this as this_ph,
)

_WINDOW_COLS = ("_pw_window", "_pw_window_start", "_pw_window_end", "_pw_key")


class WindowJoinResult:
    """Lazy window-join result: select() with pw.left / pw.right / pw.this
    (pw.this._pw_window_start / _pw_window_end give the shared window)."""

    def __init__(
        self,
        inner: JoinResult,
        orig_left,
        orig_right,
        lflat,
        rflat,
        on_pairs=(),
    ):
        self._inner = inner
        self._orig_left = orig_left
        self._orig_right = orig_right
        self._lflat = lflat
        self._rflat = rflat
        # names equi-joined on both sides: pw.this.<name> is then the
        # coalesce of the two (reference: join condition columns are
        # unambiguous on the join result)
        self._on_names = {
            l_e.name
            for l_e, r_e in on_pairs
            if isinstance(l_e, ColumnReference)
            and isinstance(r_e, ColumnReference)
            and l_e.name == r_e.name
        }

    def _pre_sub(self, e):
        lflat, rflat = self._lflat, self._rflat

        def sub(ref: ColumnReference):
            tbl = ref.table
            if tbl is self._orig_left or tbl is left_ph:
                if ref.name == "id":
                    return ColumnReference(lflat, "id")
                return lflat[ref.name]
            if tbl is self._orig_right or tbl is right_ph:
                if ref.name == "id":
                    return ColumnReference(rflat, "id")
                return rflat[ref.name]
            if isinstance(tbl, ThisPlaceholder):
                if ref.name in _WINDOW_COLS:
                    return CoalesceExpression(
                        lflat[ref.name], rflat[ref.name]
                    )
                in_l = ref.name in self._orig_left.column_names()
                in_r = ref.name in self._orig_right.column_names()
                if in_l and in_r:
                    if ref.name in self._on_names:
                        return CoalesceExpression(
                            lflat[ref.name], rflat[ref.name]
                        )
                    raise ValueError(
                        f"column {ref.name!r} is ambiguous in window_join; "
                        "use pw.left/pw.right"
                    )
                if in_l:
                    return lflat[ref.name]
                if in_r:
                    return rflat[ref.name]
                raise ValueError(f"unknown column {ref.name!r}")
            return None

        return wrap_expr(e)._substitute(sub)

    def _expand_side(self, exprs: dict, table) -> None:
        for n in table.column_names():
            if not n.startswith(("_on", "_pw_")):
                exprs[n] = table[n]

    def select(self, *args: Any, **kwargs: Any):
        exprs: dict[str, Any] = {}
        for arg in args:
            if isinstance(arg, ColumnReference):
                exprs[arg.name] = arg
            elif isinstance(arg, ThisPlaceholder):  # `*pw.left` expansion
                if arg is left_ph or arg is this_ph:
                    self._expand_side(exprs, self._orig_left)
                if arg is right_ph or arg is this_ph:
                    self._expand_side(exprs, self._orig_right)
            else:
                raise TypeError(f"positional select argument {arg!r}")
        for name, e in kwargs.items():
            if isinstance(e, ThisPlaceholder):  # `**pw.left` expansion
                if e is left_ph or e is this_ph:
                    self._expand_side(exprs, self._orig_left)
                if e is right_ph or e is this_ph:
                    self._expand_side(exprs, self._orig_right)
                continue
            exprs[name] = e
        resolved = {n: self._pre_sub(e) for n, e in exprs.items()}
        return self._inner.select(**resolved)


def _window_join_flattened(left, right, lflat, rflat, on, mode: JoinMode):
    """Equijoin the flattened sides on window identity + user conditions."""
    conds = [lflat._pw_window == rflat._pw_window]
    # rewrite user on-conditions onto the flattened tables (same column names)
    tmp = JoinResult(left, right, on, JoinMode.INNER)
    for l_e, r_e in zip(tmp._left_on, tmp._right_on):

        def remap(flat, orig):
            def sub(ref: ColumnReference):
                if ref.table is orig:
                    return flat[ref.name]
                return None

            return sub

        conds.append(
            l_e._substitute(remap(lflat, left))
            == r_e._substitute(remap(rflat, right))
        )
    inner = JoinResult(lflat, rflat, conds, mode)
    return WindowJoinResult(
        inner, left, right, lflat, rflat,
        on_pairs=list(zip(tmp._left_on, tmp._right_on)),
    )


def _session_window_join(
    win, left, right, left_time, right_time, on, mode, behavior=None
):
    """Sessions over the union of both sides' times, per join group."""
    from pathway_tpu.internals.table import Table
    from pathway_tpu.stdlib.temporal.temporal_behavior import (
        apply_behavior_to_side,
    )

    tmp = JoinResult(left, right, on, JoinMode.INNER)
    ltime = desugar(left_time, {left_ph: left, this_ph: left})
    rtime = desugar(right_time, {right_ph: right, this_ph: right})

    def prep_side(table, time_e, on_exprs, side):
        cols = {n: table[n] for n in table.column_names()}
        return table._build_rowwise(
            {
                **cols,
                "_pw_key": time_e,
                "_pw_on": make_tuple(*on_exprs) if on_exprs else None,
                "_pw_orig": table.id,
                "_pw_side": side,
            }
        )

    lprep = prep_side(left, ltime, tmp._left_on, 0)
    rprep = prep_side(right, rtime, tmp._right_on, 1)
    lmin = lprep.select(
        _pw_key=lprep._pw_key, _pw_on=lprep._pw_on,
        _pw_orig=lprep._pw_orig, _pw_side=lprep._pw_side,
    )
    rmin = rprep.select(
        _pw_key=rprep._pw_key, _pw_on=rprep._pw_on,
        _pw_orig=rprep._pw_orig, _pw_side=rprep._pw_side,
    )
    # behavior (delay / cutoff / forget) filters each record by its own time
    # before sessions are formed over the union
    lmin = apply_behavior_to_side(lmin, "_pw_key", behavior)
    rmin = apply_behavior_to_side(rmin, "_pw_key", behavior)
    comb = lmin.concat_reindex(rmin)
    node = SessionAssignNode(
        comb._node, "_pw_key", "_pw_on", win.predicate, win.max_gap
    )
    sess = Table._from_node(
        node,
        {"_pw_window_start": dt.ANY, "_pw_window_end": dt.ANY},
        comb._universe,
    )
    windows = comb.select(
        _pw_orig=comb._pw_orig,
        _pw_side=comb._pw_side,
        _pw_on=comb._pw_on,
        _pw_window_start=sess._pw_window_start,
        _pw_window_end=sess._pw_window_end,
    )

    def flat_for(orig, side):
        sw = windows.filter(windows._pw_side == side)
        sw = sw.with_id(sw._pw_orig)
        # sw's keys ARE orig row ids (one window row per source row)
        sw.promise_universe_is_subset_of(orig)
        cols = {n: orig[n] for n in orig.column_names()}
        out = orig._build_rowwise(
            {
                **cols,
                "_pw_key": (ltime if side == 0 else rtime),
                "_pw_window_start": sw._pw_window_start,
                "_pw_window_end": sw._pw_window_end,
                "_pw_window": make_tuple(
                    sw._pw_on, sw._pw_window_start, sw._pw_window_end
                ),
            }
        )
        # rows removed by behavior (or not yet assigned) have no window —
        # keep them out of the join so None windows never match each other
        return out.filter(out._pw_window_start.is_not_none())

    lflat = flat_for(left, 0)
    rflat = flat_for(right, 1)
    conds = [lflat._pw_window == rflat._pw_window]
    inner = JoinResult(lflat, rflat, conds, mode)
    return WindowJoinResult(
        inner, left, right, lflat, rflat,
        on_pairs=list(zip(tmp._left_on, tmp._right_on)),
    )


def _validate_window_join_types(
    left, right, left_time, right_time, window, on
) -> None:
    """Build-time validation of both time columns against the window's
    parameters, plus join-condition typing (reference: window joins'
    check_joint_types over eval_type)."""
    from pathway_tpu.stdlib.temporal._window import (
        _SessionWindow,
        _SlidingWindow,
    )
    from pathway_tpu.stdlib.temporal.utils import (
        check_joint_kinds,
        expr_kind,
        validate_join_condition_types,
        value_kind,
    )

    def kind_of(table, expr):
        e = desugar(expr, {left_ph: left, right_ph: right, this_ph: table})
        return expr_kind(table, e)

    params = {
        "left_time_expression": (kind_of(left, left_time), "time"),
        "right_time_expression": (kind_of(right, right_time), "time"),
    }
    if isinstance(window, _SlidingWindow):
        params["window.hop"] = (value_kind(window.hop), "interval")
        if not getattr(window, "_tumbling", False) and window.duration is not None:
            params["window.duration"] = (
                value_kind(window.duration),
                "interval",
            )
        params["window.origin"] = (value_kind(window.origin), "time")
    elif isinstance(window, _SessionWindow):
        params["window.max_gap"] = (value_kind(window.max_gap), "interval")
    check_joint_kinds(params)
    tmp = JoinResult(left, right, on, JoinMode.INNER)
    validate_join_condition_types(left, right, tmp._left_on, tmp._right_on)


def window_join(
    self, other, self_time, other_time, window, *on,
    how: JoinMode = JoinMode.INNER, behavior=None,
) -> WindowJoinResult:
    """Pair rows of `self` and `other` that share a window over their
    respective time columns (plus `on` equality conditions)."""
    _validate_window_join_types(self, other, self_time, other_time, window, on)
    return window._join(self, other, self_time, other_time, on, how, behavior)


def window_join_inner(self, other, self_time, other_time, window, *on, **kw):
    return window_join(
        self, other, self_time, other_time, window, *on, how=JoinMode.INNER,
        **kw,
    )


def window_join_left(self, other, self_time, other_time, window, *on, **kw):
    return window_join(
        self, other, self_time, other_time, window, *on, how=JoinMode.LEFT,
        **kw,
    )


def window_join_right(self, other, self_time, other_time, window, *on, **kw):
    return window_join(
        self, other, self_time, other_time, window, *on, how=JoinMode.RIGHT,
        **kw,
    )


def window_join_outer(self, other, self_time, other_time, window, *on, **kw):
    return window_join(
        self, other, self_time, other_time, window, *on, how=JoinMode.OUTER,
        **kw,
    )
