"""pw.ordered — order-aware helpers (reference: stdlib/ordered/diff.py)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.dtype as dt
from pathway_tpu.internals.expression import ColumnReference


def diff(
    table,
    timestamp: Any,
    *values: ColumnReference,
    instance: Any = None,
) -> Any:
    """Compute per-row difference vs the previous row in timestamp order
    (reference: stdlib/ordered/diff.py, built on sort prev/next pointers)."""
    import pathway_tpu as pw

    sorted_ptrs = table.sort(key=timestamp, instance=instance)
    with_prev = table.with_columns(_prev=sorted_ptrs.prev)
    # one indexer shared by every value column (an ix per column would
    # duplicate the full table state per diffed column)
    prev_rows = table.ix(with_prev._prev, optional=True)
    out_cols = {}
    for v in values:
        name = f"diff_{v.name}"
        # first row per instance has no predecessor: None, not an error
        out_cols[name] = pw.require(
            v - prev_rows[v.name], prev_rows[v.name]
        )
    return table.select(**out_cols)


__all__ = ["diff"]
