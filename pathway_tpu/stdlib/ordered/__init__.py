"""pw.ordered — order-aware helpers (reference: stdlib/ordered/diff.py)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.dtype as dt
from pathway_tpu.internals.expression import ColumnReference


def diff(
    table,
    timestamp: Any,
    *values: ColumnReference,
    instance: Any = None,
) -> Any:
    """Compute per-row difference vs the previous row in timestamp order
    (reference: stdlib/ordered/diff.py, built on sort prev/next pointers)."""
    sorted_ptrs = table.sort(key=timestamp, instance=instance)
    with_prev = table.with_columns(_prev=sorted_ptrs.prev)
    out_cols = {}
    for v in values:
        name = f"diff_{v.name}"
        prev_rows = table.ix(with_prev._prev, optional=True)
        out_cols[name] = v - prev_rows[v.name]
    return table.select(**out_cols)


__all__ = ["diff"]
