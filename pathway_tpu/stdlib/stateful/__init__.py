"""pw.stateful (reference: stdlib/stateful/deduplicate.py)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    value: Any = None,
    instance: Any = None,
    acceptor: Callable | None = None,
    name: str | None = None,
    persistent_id: str | None = None,
):
    """Keep only the last accepted value per instance."""
    return table.deduplicate(
        value=value,
        instance=instance,
        acceptor=acceptor,
        name=name,
        persistent_id=persistent_id,
    )


__all__ = ["deduplicate"]
