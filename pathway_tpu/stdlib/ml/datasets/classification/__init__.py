"""Classification datasets (reference: stdlib/ml/datasets/classification —
MNIST via sklearn's fetch_openml, split 6/7 train, 1/7 test)."""

from __future__ import annotations


def load_mnist_sample(sample_size: int = 70000):
    """(X_train, y_train, X_test, y_test) tables of MNIST vectors/labels.
    Requires scikit-learn and network access to openml.org at call time."""
    import numpy as np
    import pandas as pd

    try:
        from sklearn.datasets import fetch_openml
    except ImportError as e:  # pragma: no cover - sklearn not baked in
        raise ImportError(
            "load_mnist_sample requires scikit-learn, which is not "
            "installed in this environment"
        ) from e

    from pathway_tpu.debug import table_from_pandas

    X, y = fetch_openml(
        "mnist_784", version=1, return_X_y=True, as_frame=False
    )
    X = X / 255.0
    train_size = int(sample_size * 6 / 7)
    test_size = int(sample_size / 7)
    X_train, y_train = X[:60000][:train_size], y[:60000][:train_size]
    X_test, y_test = X[60000:70000][:test_size], y[60000:70000][:test_size]

    def vec_table(arr):
        return table_from_pandas(
            pd.DataFrame({"data": [np.array(v) for v in arr.tolist()]})
        )

    def label_table(arr):
        return table_from_pandas(pd.DataFrame({"label": arr.tolist()}))

    return (
        vec_table(X_train),
        label_table(y_train),
        vec_table(X_test),
        label_table(y_test),
    )


load_mnist_stream = load_mnist_sample

__all__ = ["load_mnist_sample", "load_mnist_stream"]
