"""Bundled ML datasets (reference: stdlib/ml/datasets)."""

from . import classification

__all__ = ["classification"]
