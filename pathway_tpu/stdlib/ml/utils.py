"""ML stdlib helpers (reference: stdlib/ml/utils.py)."""

from __future__ import annotations

from typing import Callable

from pathway_tpu.internals.table import Table


def _predict_asof_now(
    prediction_function: Callable,
    *queries,
    with_queries_universe: bool = False,
):
    """Wrap a prediction function so each query is answered once, as-of-now
    (reference: stdlib/ml/utils.py — forget + asof-now join pattern)."""
    result = prediction_function(*queries)
    if with_queries_universe and queries:
        q_table = queries[0].table
        result = result.with_universe_of(q_table)
    return result


def classifier_accuracy(predicted, exact):
    import pathway_tpu as pw

    joined = predicted.join(exact, predicted.id == exact.id).select(
        ok=pw.left.predicted_label == pw.right.label
    )
    return joined.groupby(joined.ok).reduce(
        joined.ok, count=pw.reducers.count()
    )
