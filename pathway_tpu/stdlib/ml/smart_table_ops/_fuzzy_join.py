"""Smart fuzzy join — normalized feature matching with a heavy/light
split and mutual-best selection (reference:
python/pathway/stdlib/ml/smart_table_ops/_fuzzy_join.py:1-711).

Algorithm (independent implementation of the reference's design):

1. Feature generation: each row's matching column(s) expand to features
   (words via TOKENIZE, alphanumeric characters via LETTERS), producing an
   edges table (node, feature, weight).
2. Feature informativeness: a feature occurring in cnt rows contributes
   normalize(cnt) — LOGWEIGHT 1/ceil(log2(cnt+1)), WEIGHT
   1/2^ceil(log2 cnt), NONE cnt — so ubiquitous tokens barely count.
3. Heavy/light split (HEAVY_LIGHT_THRESHOLD): pairs are *generated* only
   through light (rare) features, avoiding the quadratic blow-up of
   joining on stop-words; heavy features then add their weight only to
   pairs already generated.
4. Mutual best: per left node keep its best-scoring right (ties broken by
   a (weight, min_id, max_id) pseudoweight), then per right node keep its
   best left — only mutually-best pairs survive.
5. ``by_hand_match`` pins (left, right, weight) decisions: pinned nodes
   are excluded from matching and the pins override the result rows.
6. Projections: column-bucket projections run one fuzzy match per bucket
   and sum the per-pair weights across buckets.
"""

from __future__ import annotations

import math
from enum import IntEnum, auto
from typing import Any, Callable

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import apply_with_type, if_else, make_tuple
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


def _tokenize(obj: Any) -> tuple:
    return tuple(str(obj).split())


def _letters(obj: Any) -> tuple:
    return tuple(c.lower() for c in str(obj) if c.isalnum())


class FuzzyJoinFeatureGeneration(IntEnum):
    AUTO = auto()
    TOKENIZE = auto()
    LETTERS = auto()

    @property
    def generate(self) -> Callable[[Any], tuple]:
        if self == FuzzyJoinFeatureGeneration.LETTERS:
            return _letters
        return _tokenize


def _discrete_weight(cnt: float) -> float:
    if cnt == 0:
        return 0.0
    return 1 / (2 ** math.ceil(math.log2(cnt)))


def _discrete_logweight(cnt: float) -> float:
    if cnt == 0:
        return 0.0
    return 1 / math.ceil(math.log2(cnt + 1))


class FuzzyJoinNormalization(IntEnum):
    WEIGHT = auto()
    LOGWEIGHT = auto()
    NONE = auto()

    @property
    def normalize(self) -> Callable[[float], float]:
        if self == FuzzyJoinNormalization.WEIGHT:
            return _discrete_weight
        if self == FuzzyJoinNormalization.LOGWEIGHT:
            return _discrete_logweight
        return lambda cnt: cnt


# backwards-compatible aliases of the round-2 surface
class JoinNormalization(IntEnum):
    NONE = FuzzyJoinNormalization.NONE
    LOG = FuzzyJoinNormalization.LOGWEIGHT


def _edges_for(table: Table, col_name: str, generate) -> Table:
    e = table.select(
        node=this.id,
        feats=apply_with_type(generate, tuple, table[col_name]),
    ).flatten(this.feats)
    return e.select(node=e.node, feature=e.feats, weight=1.0)


def smart_fuzzy_match(
    left_col,
    right_col,
    *,
    by_hand_match: Table | None = None,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
    HEAVY_LIGHT_THRESHOLD: int = 100,
    include_pins: bool = True,
) -> Table:
    """Match rows whose ``left_col`` / ``right_col`` values share rare
    features. Returns a (left, right, weight) table of mutually-best pairs
    (reference: smart_fuzzy_match, _fuzzy_join.py:200)."""
    left = left_col.table
    right = right_col.table
    symmetric = left is right and left_col.name == right_col.name
    generate = FuzzyJoinFeatureGeneration(feature_generation).generate
    normalization = FuzzyJoinNormalization(normalization)

    edges_left = _edges_for(left, left_col.name, generate)
    edges_right = (
        edges_left if symmetric else _edges_for(right, right_col.name, generate)
    )
    return _fuzzy_match(
        edges_left,
        edges_right,
        symmetric=symmetric,
        normalization=normalization,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        by_hand_match=by_hand_match,
        include_pins=include_pins,
    )


def fuzzy_self_match(
    col,
    *,
    by_hand_match: Table | None = None,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
    HEAVY_LIGHT_THRESHOLD: int = 100,
) -> Table:
    return smart_fuzzy_match(
        col,
        col,
        by_hand_match=by_hand_match,
        normalization=normalization,
        feature_generation=feature_generation,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
    )


def fuzzy_match(
    edges_left: Table,
    edges_right: Table,
    features: Table,
    by_hand_match: Table | None = None,
    HEAVY_LIGHT_THRESHOLD: int = 100,
) -> Table:
    """Edge-level API (reference: fuzzy_match, _fuzzy_join.py:265): edges
    are (node, feature, weight) with feature pointing into a features
    table carrying (weight, normalization_type)."""
    return _fuzzy_match(
        edges_left,
        edges_right,
        symmetric=False,
        normalization=FuzzyJoinNormalization.LOGWEIGHT,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        by_hand_match=by_hand_match,
        features=features,
    )


def fuzzy_match_with_hint(
    edges_left: Table,
    edges_right: Table,
    features: Table,
    by_hand_match: Table,
    HEAVY_LIGHT_THRESHOLD: int = 100,
) -> Table:
    return fuzzy_match(
        edges_left,
        edges_right,
        features,
        by_hand_match=by_hand_match,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
    )


def _fuzzy_match(
    edges_left: Table,
    edges_right: Table,
    *,
    symmetric: bool,
    normalization: FuzzyJoinNormalization,
    HEAVY_LIGHT_THRESHOLD: int,
    by_hand_match: Table | None,
    features: Table | None = None,
    include_pins: bool = True,
) -> Table:
    import pathway_tpu as pw

    if by_hand_match is not None:
        # pinned nodes do not participate in automatic matching
        # (reference: _filter_out_matched_by_hand, _fuzzy_join.py:300);
        # in symmetric mode the single shared edges table must drop BOTH
        # the pins' left and right nodes
        def _without(edges: Table, pinned: Table) -> Table:
            return edges.difference(
                edges.join(
                    pinned, edges.node == pinned.node, id=edges.id
                ).select()
            )

        pinned_l = by_hand_match.select(node=by_hand_match.left)
        pinned_r = by_hand_match.select(node=by_hand_match.right)
        if symmetric:
            edges_left = _without(_without(edges_left, pinned_l), pinned_r)
            edges_right = edges_left
        else:
            edges_left = _without(edges_left, pinned_l)
            edges_right = _without(edges_right, pinned_r)

    # feature occurrence counts over BOTH sides (one side when symmetric)
    if symmetric:
        all_edges = edges_left
    else:
        all_edges = Table.concat_reindex(
            edges_left.select(feature=edges_left.feature),
            edges_right.select(feature=edges_right.feature),
        )
    feat_cnt = all_edges.groupby(all_edges.feature).reduce(
        feature=all_edges.feature, cnt=reducers.count()
    )
    if features is not None:
        # explicit features table: per-feature base weight and
        # normalization type (reference Feature schema)
        fj = feat_cnt.join(features, feat_cnt.feature == features.id)
        feat_w = fj.select(
            feature=feat_cnt.feature,
            cnt=feat_cnt.cnt,
            nweight=apply_with_type(
                lambda c, w, nt: float(w)
                * float(FuzzyJoinNormalization(nt).normalize(float(c))),
                float,
                feat_cnt.cnt,
                features.weight,
                features.normalization_type,
            ),
        )
    else:
        norm = normalization.normalize
        feat_w = feat_cnt.select(
            feature=feat_cnt.feature,
            cnt=feat_cnt.cnt,
            nweight=apply_with_type(
                lambda c: float(norm(float(c))), float, feat_cnt.cnt
            ),
        )

    def annotate(edges: Table) -> Table:
        j = edges.join(feat_w, edges.feature == feat_w.feature)
        return j.select(
            node=edges.node,
            feature=edges.feature,
            weight=edges.weight,
            cnt=feat_w.cnt,
            nweight=feat_w.nweight,
        )

    el = annotate(edges_left)
    er = el if symmetric else annotate(edges_right)
    el_light = el.filter(el.cnt < HEAVY_LIGHT_THRESHOLD)
    el_heavy = el.filter(el.cnt >= HEAVY_LIGHT_THRESHOLD)
    er_light = er.filter(er.cnt < HEAVY_LIGHT_THRESHOLD)
    er_heavy = er.filter(er.cnt >= HEAVY_LIGHT_THRESHOLD)

    # candidate pairs come from LIGHT features only
    light_pairs = el_light.join(
        er_light, el_light.feature == er_light.feature
    ).select(
        left=pw.left.node,
        right=pw.right.node,
        w=pw.left.weight * pw.right.weight * pw.left.nweight,
    )
    if symmetric:
        light_pairs = light_pairs.filter(light_pairs.left != light_pairs.right)
    light_sum = light_pairs.groupby(light_pairs.left, light_pairs.right).reduce(
        left=light_pairs.left,
        right=light_pairs.right,
        w=reducers.sum(light_pairs.w),
    )

    # heavy features reinforce already-generated pairs only
    heavy_pairs = (
        light_sum.join(el_heavy, light_sum.left == el_heavy.node)
        .select(
            left=pw.left.left,
            right=pw.left.right,
            feature=pw.right.feature,
            lw=pw.right.weight,
            nweight=pw.right.nweight,
        )
        .join(
            er_heavy,
            pw.left.right == er_heavy.node,
            pw.left.feature == er_heavy.feature,
        )
        .select(
            left=pw.left.left,
            right=pw.left.right,
            w=pw.left.lw * pw.right.weight * pw.left.nweight,
        )
    )
    total = Table.concat_reindex(light_sum, heavy_pairs)
    scored = total.groupby(total.left, total.right).reduce(
        left=total.left, right=total.right, w=reducers.sum(total.w)
    )
    # deterministic tie-break: (weight, smaller id, larger id)
    pseudo = scored.select(
        left=scored.left,
        right=scored.right,
        pweight=if_else(
            scored.left < scored.right,
            make_tuple(scored.w, scored.left, scored.right),
            make_tuple(scored.w, scored.right, scored.left),
        ),
    )
    best_l = pseudo.groupby(pseudo.left).reduce(
        left=pseudo.left,
        right=reducers.argmax(pseudo.pweight, pseudo.right),
        pweight=reducers.max(pseudo.pweight),
    )
    best = best_l.groupby(best_l.right).reduce(
        right=best_l.right,
        left=reducers.argmax(best_l.pweight, best_l.left),
        pweight=reducers.max(best_l.pweight),
    )
    result = best.select(
        left=best.left,
        right=best.right,
        weight=apply_with_type(lambda t: float(t[0]), float, best.pweight),
    )
    if symmetric:
        result = result.filter(result.left < result.right)
    if by_hand_match is not None and include_pins:
        pins = by_hand_match.select(
            left=by_hand_match.left,
            right=by_hand_match.right,
            weight=by_hand_match.weight,
        )
        result = Table.concat_reindex(result, pins)
    return result


def _concat_desc(table: Table) -> Table:
    cols = [table[n] for n in table.column_names()]
    return table.select(
        desc=apply_with_type(
            lambda *a: " ".join(str(x) for x in a), str, *cols
        )
    )


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    by_hand_match: Table | None = None,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
    left_projection: dict[str, str] | None = None,
    right_projection: dict[str, str] | None = None,
    HEAVY_LIGHT_THRESHOLD: int = 100,
) -> Table:
    """Fuzzy-match whole rows (all columns concatenated), optionally per
    projection bucket. Output columns (left, right, weight) follow the
    reference's JoinResult schema (reference: fuzzy_match_tables,
    _fuzzy_join.py:104)."""
    left_projection = left_projection or {}
    right_projection = right_projection or {}
    if not left_projection or not right_projection:
        left = _concat_desc(left_table)
        right = _concat_desc(right_table)
        return smart_fuzzy_match(
            left.desc,
            right.desc,
            by_hand_match=by_hand_match,
            normalization=normalization,
            feature_generation=feature_generation,
            HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        )
    buckets: dict[str, tuple[list, list]] = {}
    for col, b in left_projection.items():
        buckets.setdefault(b, ([], []))[0].append(col)
    for col, b in right_projection.items():
        buckets.setdefault(b, ([], []))[1].append(col)
    partials = []
    for b, (lcols, rcols) in buckets.items():
        if not lcols or not rcols:
            continue
        lb = _concat_desc(left_table.select(*[left_table[c] for c in lcols]))
        rb = _concat_desc(right_table.select(*[right_table[c] for c in rcols]))
        partials.append(
            smart_fuzzy_match(
                lb.desc,
                rb.desc,
                by_hand_match=by_hand_match,
                normalization=normalization,
                feature_generation=feature_generation,
                HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
                # pins appended once below, not once per bucket
                include_pins=False,
            )
        )
    matchings = Table.concat_reindex(*partials)
    summed = matchings.groupby(matchings.left, matchings.right).reduce(
        matchings.left,
        matchings.right,
        weight=reducers.sum(matchings.weight),
    )
    if by_hand_match is not None:
        pins = by_hand_match.select(
            left=by_hand_match.left,
            right=by_hand_match.right,
            weight=by_hand_match.weight,
        )
        summed = Table.concat_reindex(summed, pins)
    return summed


def smart_fuzzy_join(
    left: Table,
    right: Table,
    reflexive: bool = False,
    normalization: Any = None,
    **kwargs: Any,
) -> Table:
    """Round-2 compatibility wrapper: case-insensitive match on the first
    string columns, output (left_id, right_id, weight)."""
    lcol = left.column_names()[0]
    rcol = right.column_names()[0]
    # the historical surface lowercased before tokenizing; the reference's
    # _tokenize (and ours) does not, so normalize here
    llow = left.select(
        _fj=apply_with_type(lambda s: str(s).lower(), str, left[lcol])
    )
    rlow = right.select(
        _fj=apply_with_type(lambda s: str(s).lower(), str, right[rcol])
    )
    if normalization is None:
        norm = FuzzyJoinNormalization.LOGWEIGHT
    else:
        norm = FuzzyJoinNormalization(
            JoinNormalization(normalization)
            if isinstance(normalization, JoinNormalization)
            else normalization
        )
    res = smart_fuzzy_match(llow._fj, rlow._fj, normalization=norm)
    return res.select(
        left_id=res.left, right_id=res.right, weight=res.weight
    )
