"""Fuzzy join ops (reference: stdlib/ml/smart_table_ops/_fuzzy_join.py,
711 LoC). Minimal capability: fuzzy self/cross match by feature overlap."""

from __future__ import annotations

from enum import Enum
from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


class JoinNormalization(Enum):
    NONE = "none"
    LOG = "log"


def smart_fuzzy_join(
    left: Table,
    right: Table,
    reflexive: bool = False,
    normalization: Any = JoinNormalization.LOG,
    **kwargs: Any,
) -> Table:
    """Match rows of `left` to rows of `right` by token overlap of their
    first string column. Returns (left_id, right_id, weight)."""
    import math

    import pathway_tpu as pw

    lcol = left.column_names()[0]
    rcol = right.column_names()[0]

    def tokens(s: str) -> tuple:
        return tuple(str(s).lower().split())

    l_tok = left.select(
        lid=this.id, toks=apply_with_type(tokens, tuple, left[lcol])
    ).flatten(this.toks)
    r_tok = right.select(
        rid=this.id, toks=apply_with_type(tokens, tuple, right[rcol])
    ).flatten(this.toks)
    pairs = l_tok.join(r_tok, l_tok.toks == r_tok.toks).select(
        lid=pw.left.lid, rid=pw.right.rid
    )
    weights = pairs.groupby(pairs.lid, pairs.rid).reduce(
        left_id=pairs.lid,
        right_id=pairs.rid,
        weight=reducers.count(),
    )
    best = weights.groupby(this.left_id).reduce(
        match_id=reducers.argmax(this.weight)
    )
    return weights.having(best.match_id)


def fuzzy_match_tables(left: Table, right: Table, **kwargs: Any) -> Table:
    return smart_fuzzy_join(left, right, **kwargs)
