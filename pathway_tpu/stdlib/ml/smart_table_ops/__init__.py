"""Smart table ops — normalized fuzzy join family (reference:
python/pathway/stdlib/ml/smart_table_ops/_fuzzy_join.py:1-711)."""

from pathway_tpu.stdlib.ml.smart_table_ops._fuzzy_join import (
    FuzzyJoinFeatureGeneration,
    FuzzyJoinNormalization,
    JoinNormalization,
    fuzzy_match,
    fuzzy_match_tables,
    fuzzy_match_with_hint,
    fuzzy_self_match,
    smart_fuzzy_join,
    smart_fuzzy_match,
)

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "JoinNormalization",
    "fuzzy_match",
    "fuzzy_match_tables",
    "fuzzy_match_with_hint",
    "fuzzy_self_match",
    "smart_fuzzy_join",
    "smart_fuzzy_match",
]
