"""KNN classifiers (reference: stdlib/ml/classifiers/_knn_lsh.py —
LSH-bucketed KNN vote; here the candidate search runs on TPU)."""

from __future__ import annotations

from enum import Enum
from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


class DistanceTypes(Enum):
    EUCLIDEAN = "euclidean"
    COSINE = "cosine"


def knn_lsh_classifier_train(
    data: Table,
    L: int = 20,
    type: str = "euclidean",
    **kwargs: Any,
):
    """Train a KNN 'classifier' — returns a function that labels query
    points by majority vote over the k nearest training rows.

    ``data`` needs columns ``data`` (vector) and ``label``."""
    from pathway_tpu.stdlib.ml.index import KNNIndex

    dim = kwargs.get("d") or kwargs.get("dimensions")
    index = KNNIndex(
        data.data, data, n_dimensions=dim, distance_type=str(type)
    )

    def label_query(queries: Table, k: int = 3) -> Table:
        matches = index.get_nearest_items(queries.data, k=k)

        def majority(labels) -> Any:
            if not labels:
                return None
            counts: dict = {}
            for l in labels:
                counts[l] = counts.get(l, 0) + 1
            return max(counts.items(), key=lambda kv: (kv[1], str(kv[0])))[0]

        return matches.select(
            predicted_label=apply_with_type(majority, Any, matches.label)
        )

    label_query._train_args = (data, L, type, dict(kwargs))
    return label_query


def knn_lsh_train(*args, **kwargs):
    return knn_lsh_classifier_train(*args, **kwargs)


def knn_lsh_generic_classifier_train(*args, **kwargs):
    return knn_lsh_classifier_train(*args, **kwargs)


def knn_lsh_euclidean_classifier_train(data, d, M, L, A, **kwargs):
    """Euclidean-LSH-parameterized trainer (reference: _knn_lsh.py:293).
    The TPU build's candidate search is exact dense top-k, so the LSH
    parameters select the distance metric; d doubles as the dimension
    hint."""
    return knn_lsh_classifier_train(data, L=L, type="euclidean", d=d, **kwargs)


def knn_lsh_classify(knn_model, data_labels, queries, k):
    """Classify queries by majority vote over the k nearest training rows
    (reference: _knn_lsh.py:306). ``data_labels`` must share the training
    table's universe (one label per training row); its labels override any
    label column the model was trained with."""
    cache = getattr(knn_model, "_classify_cache", None)
    if cache is None:
        cache = knn_model._classify_cache = {}
    entry = cache.get(id(data_labels))
    if entry is None or entry[0] is not data_labels:
        data, L, type_, kwargs = knn_model._train_args
        labels = data_labels.restrict(data)
        enriched = data.with_columns(label=labels.label)
        relabeled = knn_lsh_classifier_train(
            enriched, L=L, type=type_, **kwargs
        )
        # hold data_labels so id() can't alias a collected table, and so
        # repeated classify calls reuse one index build
        cache[id(data_labels)] = (data_labels, relabeled)
        entry = cache[id(data_labels)]
    return entry[1](queries, k=k)


from pathway_tpu.stdlib.ml.classifiers._lsh import (  # noqa: E402
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
    lsh,
)
from pathway_tpu.stdlib.ml.classifiers._clustering_via_lsh import (  # noqa: E402
    clustering_via_lsh,
)

__all__ = [
    "DistanceTypes",
    "clustering_via_lsh",
    "generate_cosine_lsh_bucketer",
    "generate_euclidean_lsh_bucketer",
    "knn_lsh_classifier_train",
    "knn_lsh_classify",
    "knn_lsh_euclidean_classifier_train",
    "knn_lsh_generic_classifier_train",
    "knn_lsh_train",
    "lsh",
]
