"""KNN classifiers (reference: stdlib/ml/classifiers/_knn_lsh.py —
LSH-bucketed KNN vote; here the candidate search runs on TPU)."""

from __future__ import annotations

from enum import Enum
from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


class DistanceTypes(Enum):
    EUCLIDEAN = "euclidean"
    COSINE = "cosine"


def knn_lsh_classifier_train(
    data: Table,
    L: int = 20,
    type: str = "euclidean",
    **kwargs: Any,
):
    """Train a KNN 'classifier' — returns a function that labels query
    points by majority vote over the k nearest training rows.

    ``data`` needs columns ``data`` (vector) and ``label``."""
    from pathway_tpu.stdlib.ml.index import KNNIndex

    dim = kwargs.get("d") or kwargs.get("dimensions")
    index = KNNIndex(
        data.data, data, n_dimensions=dim, distance_type=str(type)
    )

    def label_query(queries: Table, k: int = 3) -> Table:
        matches = index.get_nearest_items(queries.data, k=k)

        def majority(labels) -> Any:
            if not labels:
                return None
            counts: dict = {}
            for l in labels:
                counts[l] = counts.get(l, 0) + 1
            return max(counts.items(), key=lambda kv: (kv[1], str(kv[0])))[0]

        return matches.select(
            predicted_label=apply_with_type(majority, Any, matches.label)
        )

    return label_query


def knn_lsh_train(*args, **kwargs):
    return knn_lsh_classifier_train(*args, **kwargs)


def knn_lsh_generic_classifier_train(*args, **kwargs):
    return knn_lsh_classifier_train(*args, **kwargs)
