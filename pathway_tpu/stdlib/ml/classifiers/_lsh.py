"""LSH bucketers + the flattening `lsh` operator (reference:
stdlib/ml/classifiers/_lsh.py). Bucketers hash vectors into L band
buckets (M ANDs per band); `lsh` expands each row into its (band,
bucket) pairs as a table — the candidate-generation stage of the LSH KNN
and clustering pipelines."""

from __future__ import annotations

import hashlib
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


def _fingerprint_i32(arr: np.ndarray) -> int:
    """Stable 32-bit fingerprint of an int vector (reference: fingerprints
    .fingerprint(format='i32'))."""
    h = hashlib.blake2b(
        np.ascontiguousarray(arr.astype(np.int64)).tobytes(), digest_size=4
    )
    return int.from_bytes(h.digest(), "little", signed=True)


def generate_euclidean_lsh_bucketer(
    d: int, M: int, L: int, A: float = 1.0, seed: int = 0
) -> Callable[[np.ndarray], np.ndarray]:
    """Euclidean LSH: M random projections per band, bucket width A,
    L bands (reference: _lsh.py:31)."""
    gen = np.random.default_rng(seed=seed)
    total = M * L
    lines = gen.standard_normal((d, total))
    lines = lines / np.linalg.norm(lines, axis=0)
    shift = gen.random(size=total) * A

    def bucketify(x: np.ndarray) -> np.ndarray:
        buckets = np.floor_divide(
            np.asarray(x, dtype=float) @ lines + shift, A
        ).astype(int)
        return np.array(
            [_fingerprint_i32(band) for band in np.split(buckets, L)]
        )

    return bucketify


def generate_cosine_lsh_bucketer(
    d: int, M: int, L: int, seed: int = 0
) -> Callable[[np.ndarray], np.ndarray]:
    """Cosine LSH: sign patterns over M random hyperplanes per band
    (reference: _lsh.py:58)."""
    gen = np.random.default_rng(seed=seed)
    planes = gen.standard_normal((d, M * L))

    def bucketify(x: np.ndarray) -> np.ndarray:
        signs = (np.asarray(x, dtype=float) @ planes > 0).astype(int)
        return np.array(
            [_fingerprint_i32(band) for band in np.split(signs, L)]
        )

    return bucketify


def lsh(
    data: Table,
    bucketer: Callable,
    origin_id: str = "origin_id",
    include_data: bool = True,
) -> Table:
    """Per-row LSH expansion: one output row per (band, bucket) of each
    input row, carrying the origin row's id (and optionally its vector)
    (reference: _lsh.py:82)."""
    from pathway_tpu.internals.common import apply_with_type

    flat = data.select(
        **{origin_id: this.id},
        _pairs=apply_with_type(
            lambda x: tuple(
                (int(b), int(band)) for band, b in enumerate(bucketer(x))
            ),
            tuple,
            data.data,
        ),
    ).flatten(this._pairs)
    out = flat.select(
        flat[origin_id],
        bucketing=apply_with_type(lambda p: p[0], int, flat._pairs),
        band=apply_with_type(lambda p: p[1], int, flat._pairs),
    )
    if include_data:
        out = out.with_columns(data=data.ix(out[origin_id]).data)
    return out
