"""(Pre)clustering via LSH (reference:
stdlib/ml/classifiers/_clustering_via_lsh.py:1-79): bucket rows with an
LSH bucketer, average each (bucket, band) into a weighted representative,
KMeans the representatives, then label each row by majority vote over its
buckets' cluster labels."""

from __future__ import annotations

import numpy as np

from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.ml.classifiers._lsh import lsh
from pathway_tpu.stdlib.utils.col import (
    apply_all_rows,
    groupby_reduce_majority,
)

import pathway_tpu.reducers as reducers


def clustering_via_lsh(data: Table, bucketer, k: int) -> Table:
    """Label each row of ``data`` (column ``data``: np.ndarray) with a
    cluster id in [0, k). Requires scikit-learn at call time."""
    flat = lsh(data, bucketer, origin_id="data_id", include_data=True)

    representatives = (
        flat.groupby(flat.bucketing, flat.band)
        .reduce(
            flat.bucketing,
            flat.band,
            sum=reducers.sum(flat.data),
            count=reducers.count(),
        )
        .select(
            this.bucketing,
            this.band,
            data=apply_with_type(
                lambda s, c: np.asarray(s) / c, np.ndarray, this.sum, this.count
            ),
            weight=this.count,
        )
    )

    def clustering(vecs, weights):
        from sklearn.cluster import KMeans

        km = KMeans(n_clusters=k, init="k-means++", random_state=0, n_init=10)
        km.fit(np.stack(vecs), sample_weight=np.asarray(weights, float))
        return [int(label) for label in km.labels_]

    labels = apply_all_rows(
        representatives.data,
        representatives.weight,
        fun=clustering,
        result_col_name="label",
    )
    representatives = representatives.with_columns(labels)

    votes = flat.join(
        representatives,
        flat.bucketing == representatives.bucketing,
        flat.band == representatives.band,
    ).select(flat.data_id, representatives.label)

    result = groupby_reduce_majority(votes.data_id, votes.label)
    relabeled = result.select(label=result.majority, _nid=result.data_id)
    return relabeled.with_id(relabeled._nid).without("_nid")
