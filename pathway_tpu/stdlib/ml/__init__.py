from pathway_tpu.stdlib.ml import index
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = ["index", "KNNIndex", "classifiers", "smart_table_ops", "utils"]


def __getattr__(name: str):
    import importlib

    if name in ("classifiers", "smart_table_ops", "utils", "hmm", "datasets"):
        return importlib.import_module(f"pathway_tpu.stdlib.ml.{name}")
    raise AttributeError(name)
