"""KNNIndex (reference: stdlib/ml/index.py:9 — there a pure-dataflow LSH ANN;
here exact dense KNN on the MXU, which dominates LSH at reference scales.
`distance_type` picks the metric; distances are returned in the reference's
units (euclidean distance / cosine distance)."""

from __future__ import annotations

import math
from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.colnames import _MATCHED_ID, _SCORE
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import TpuKnn


class KNNIndex:
    def __init__(
        self,
        data_embedding: ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: ColumnExpression | None = None,
    ):
        self.distance_type = distance_type
        metric = "cosine" if distance_type == "cosine" else "l2sq"
        self.inner = TpuKnn(
            data_embedding,
            metadata,
            dimensions=n_dimensions,
            metric=metric,
        )
        self.index = DataIndex(data, self.inner)
        self.data = data

    def _with_dist(self, result: Table) -> Table:
        dt_kind = self.distance_type

        def to_dists(scores) -> tuple:
            if scores is None:
                return ()
            out = []
            for s in scores:
                if dt_kind == "cosine":
                    # scores are reference-style negative distances
                    # (cos - 1), so distance = -score
                    out.append(-float(s))
                else:
                    # reference KNNIndex reports SQUARED euclidean
                    # distances (stdlib/ml/index.py get_nearest_items)
                    out.append(max(0.0, -float(s)))
            return tuple(out)

        return result.with_columns(
            dist=apply_with_type(to_dists, tuple, result[_SCORE])
        )

    def _query(
        self,
        query_embedding: ColumnReference,
        k: int,
        collapse_rows: bool,
        with_distances: bool,
        metadata_filter: ColumnExpression | None,
        as_of_now: bool,
    ):
        from pathway_tpu.internals.thisclass import right

        method = (
            self.index.query_as_of_now if as_of_now else self.index.query
        )
        jr = method(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        sel = jr.select(
            *[right[c] for c in self.data.column_names()],
            **{_SCORE: right[_SCORE]},
        )
        if with_distances:
            sel = self._with_dist(sel)
        return sel.without(_SCORE)

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ):
        return self._query(
            query_embedding,
            k,
            collapse_rows,
            with_distances,
            metadata_filter,
            as_of_now=False,
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ):
        return self._query(
            query_embedding,
            k,
            collapse_rows,
            with_distances,
            metadata_filter,
            as_of_now=True,
        )
