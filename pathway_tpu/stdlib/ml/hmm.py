"""pw.ml.hmm — hidden-markov-model state tracking as a reducer.

TPU-native counterpart of the reference's HMM helper
(reference: python/pathway/stdlib/ml/hmm.py — builds a stateful reducer
that tracks the most likely hidden state as observations stream in).
The accumulator keeps a log-probability beam over hidden states and
Viterbi-advances it per observation; use inside
``groupby(...).reduce(state=hmm_reducer(obs_column))``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Hashable

from pathway_tpu.reducers import stateful_single


@dataclass
class DenseHMM:
    """A discrete HMM: states, initial/transition log-space probabilities
    and an emission probability function p(obs | state)."""

    states: list[Hashable]
    initial: dict[Hashable, float] = field(default_factory=dict)
    transitions: dict[tuple[Hashable, Hashable], float] = field(
        default_factory=dict
    )
    emission: Callable[[Hashable, Any], float] = lambda s, o: 1.0

    def log_initial(self, s: Hashable) -> float:
        p = self.initial.get(s, 1.0 / len(self.states))
        return math.log(p) if p > 0 else -math.inf

    def log_transition(self, s0: Hashable, s1: Hashable) -> float:
        p = self.transitions.get((s0, s1), 0.0)
        return math.log(p) if p > 0 else -math.inf

    def log_emission(self, s: Hashable, obs: Any) -> float:
        p = self.emission(s, obs)
        return math.log(p) if p > 0 else -math.inf


def create_hmm_reducer(hmm: DenseHMM, beam_size: int | None = None):
    """Returns a reducer: column of observations -> beam over hidden states
    (Viterbi filtering). `stateful_single` calls the combiner once per row
    with the single observation value."""

    def combine(state, obs):
        # state: tuple of (hidden_state, logp) pairs or None
        beam = dict(state) if state else None
        if beam is None:
            beam = {
                s: hmm.log_initial(s) + hmm.log_emission(s, obs)
                for s in hmm.states
            }
        else:
            new_beam: dict[Hashable, float] = {}
            for s1 in hmm.states:
                best = -math.inf
                for s0, lp in beam.items():
                    cand = lp + hmm.log_transition(s0, s1)
                    if cand > best:
                        best = cand
                e = hmm.log_emission(s1, obs)
                if best + e > -math.inf:
                    new_beam[s1] = best + e
            beam = new_beam or beam
        if beam_size is not None and len(beam) > beam_size:
            beam = dict(
                sorted(beam.items(), key=lambda kv: -kv[1])[:beam_size]
            )
        return tuple(sorted(beam.items(), key=lambda kv: -kv[1]))

    return stateful_single(combine)


def most_likely_state(beam: tuple) -> Any:
    """Extract the argmax state from a beam produced by the hmm reducer
    (use in a select after the reduce)."""
    return beam[0][0] if beam else None
