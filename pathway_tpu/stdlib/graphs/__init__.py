"""pw.graphs — graph algorithms on tables
(reference: stdlib/graphs/: pagerank, bellman_ford, louvain_communities).
Demonstrates pw.iterate fixed-point computation."""

from __future__ import annotations

from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import coalesce, if_else
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.thisclass import this


def pagerank(edges, steps: int = 5, damping: float = 0.85):
    """PageRank over an edge table with columns (u, v): u -> v
    (reference: stdlib/graphs/pagerank/). Returns table keyed by vertex with
    column `rank` (scaled int like the reference's fixed-point ranks)."""
    import pathway_tpu as pw

    out_degree = edges.groupby(edges.u).reduce(
        edges.u, degree=reducers.count()
    )
    vertices_u = edges.groupby(edges.u).reduce(edges.u).select(v=this.u)
    vertices_v = edges.groupby(edges.v).reduce(edges.v).select(v=this.v)
    vertices = (
        vertices_u.concat_reindex(vertices_v)
        .groupby(this.v)
        .reduce(this.v)
    )

    base = vertices.select(v=this.v, rank=1.0)

    def step(ranks):
        deg = out_degree.with_id_from(this.u)
        r = ranks.with_id_from(this.v)
        contribs = edges.select(
            src=edges.u,
            dst=edges.v,
        )
        with_rank = contribs.select(
            dst=this.dst,
            contrib=r.ix(contribs.select(
                _p=ranks.pointer_from(this.src)
            )._p, optional=True).rank
            / deg.ix(contribs.select(
                _p=out_degree.pointer_from(this.src)
            )._p, optional=True).degree,
        )
        summed = with_rank.groupby(this.dst).reduce(
            v=this.dst, incoming=reducers.sum(this.contrib)
        )
        joined = ranks.select(v=this.v).with_id_from(this.v)
        s2 = summed.with_id_from(this.v)
        new_ranks = joined.select(
            v=this.v,
            rank=(1 - damping)
            + damping * coalesce(s2.restrict(joined).incoming, 0.0),
        )
        return new_ranks.with_id_from(this.v)

    ranks = base.with_id_from(this.v)
    result = iterate(
        lambda ranks: step(ranks), iteration_limit=steps, ranks=ranks
    )
    return result


def bellman_ford(vertices, edges):
    """Shortest paths from vertices where is_source=True over edges
    (u, v, dist) (reference: stdlib/graphs/bellman_ford/)."""
    import math

    import pathway_tpu as pw

    base = vertices.select(
        dist_from_source=if_else(
            this.is_source, 0.0, math.inf
        )
    )

    def step(state):
        relaxed = edges.join(
            state, edges.u == state.id
        ).select(
            v=edges.v,
            dist=state.dist_from_source + edges.dist,
        )
        best = relaxed.groupby(this.v).reduce(
            best=reducers.min(this.dist), v=this.v
        ).with_id(this.v)
        new_state = state.select(
            dist_from_source=if_else(
                best.restrict(state).best.is_not_none()
                & (coalesce(best.restrict(state).best, math.inf)
                   < this.dist_from_source),
                coalesce(best.restrict(state).best, math.inf),
                this.dist_from_source,
            )
        )
        return new_state

    return iterate(lambda state: step(state), state=base)


def modularity(edges, communities):
    """Modularity Q of a community assignment.
    (reference: stdlib/graphs/louvain_communities/ exact modularity check)

    edges: (u, v, weight); communities: keyed by vertex with column `c`.
    Returns a 1-row table with column `modularity`:
    Q = sum_c (in_c / m  -  (tot_c / 2m)^2 * 2)   [undirected convention]
    """
    cu = communities.with_id_from(this.v)
    e_p = edges.select(
        weight=this.weight,
        _pu=communities.pointer_from(this.u),
        _pv=communities.pointer_from(this.v),
    )
    e = e_p.select(
        weight=this.weight,
        cu=cu.ix(e_p._pu).c,
        cv=cu.ix(e_p._pv).c,
    )
    m_t = e.groupby().reduce(m=reducers.sum(this.weight))
    intra = e.filter(this.cu == this.cv).groupby().reduce(
        w_in=reducers.sum(this.weight)
    )
    # degree mass per community
    du = e.select(c=this.cu, w=this.weight)
    dv = e.select(c=this.cv, w=this.weight)
    deg = du.concat_reindex(dv).groupby(this.c).reduce(
        this.c, tot=reducers.sum(this.w)
    )
    sq = deg.groupby().reduce(sq=reducers.sum(this.tot * this.tot))
    # all three aggregates are single-row tables keyed by the empty-group
    # pointer, so ix on a shared constant pointer column fuses them
    one_p = m_t.select(
        m=this.m,
        _pi=intra.pointer_from(),
        _ps=sq.pointer_from(),
    )
    return one_p.select(
        modularity=coalesce(intra.ix(one_p._pi, optional=True).w_in, 0.0)
        / this.m
        - sq.ix(one_p._ps).sq / (4.0 * this.m * this.m)
    )


def _louvain_one_level(vertices, edges, iteration_limit: int = 10):
    """One Louvain level: vertices greedily adopt the neighboring community
    with the largest modularity gain until stable
    (reference: stdlib/graphs/louvain_communities/ one-level step, built on
    pw.iterate like the reference)."""
    base = vertices.select(v=this.v, c=this.v).with_id_from(this.v)
    m_t = edges.groupby().reduce(m=reducers.sum(this.weight))

    def step(comm):
        cu = comm.with_id_from(this.v)
        # incidence list: (x, y, w) both directions; look up y's community
        # via the two-step pointer pattern (compute pointer column first,
        # then ix — same as pagerank above)
        fwd = edges.select(x=this.u, y=this.v, w=this.weight)
        bwd = edges.select(x=this.v, y=this.u, w=this.weight)
        inc0 = fwd.concat_reindex(bwd)
        inc_p = inc0.select(
            x=this.x, w=this.w, _py=comm.pointer_from(this.y)
        )
        inc = inc_p.select(x=this.x, w=this.w, cy=cu.ix(inc_p._py).c)
        cand = inc.groupby(this.x, this.cy).reduce(
            this.x, this.cy, k_in=reducers.sum(this.w)
        )
        # degree of each vertex and total degree mass of each community
        deg = inc.groupby(this.x).reduce(this.x, k=reducers.sum(this.w))
        cd_p = inc.select(w=this.w, _px=comm.pointer_from(this.x))
        comm_deg = cd_p.select(
            c=cu.ix(cd_p._px).c, w=this.w
        ).groupby(this.c).reduce(this.c, tot=reducers.sum(this.w))
        cand_p = cand.select(
            x=this.x,
            cy=this.cy,
            k_in=this.k_in,
            _pd=deg.pointer_from(this.x),
            _pc=comm_deg.pointer_from(this.cy),
            _pm=m_t.pointer_from(),
            _px=comm.pointer_from(this.x),
        )
        # score(x -> cy) = k_in - k_x * tot_cy' / 2m, with x's own degree
        # excluded from its current community's total (standard Louvain ΔQ
        # up to the constant 1/m factor)
        scored = cand_p.select(
            x=this.x,
            cy=this.cy,
            cur=cu.ix(cand_p._px).c,
            gain=this.k_in
            - deg.ix(cand_p._pd).k
            * (
                coalesce(comm_deg.ix(cand_p._pc, optional=True).tot, 0.0)
                - if_else(
                    cu.ix(cand_p._px).c == this.cy,
                    deg.ix(cand_p._pd).k,
                    0.0,
                )
            )
            / (2.0 * m_t.ix(cand_p._pm).m),
        )
        # moving is worthwhile only if the best OTHER community beats
        # staying in the current one
        others = scored.filter(this.cy != this.cur)
        best = others.groupby(this.x).reduce(
            this.x,
            best_c=reducers.argmax(this.gain, this.cy),
            best_gain=reducers.max(this.gain),
        )
        b = best.with_id_from(this.x)
        stay_cand = scored.filter(this.cy == this.cur).groupby(this.x).reduce(
            this.x, stay=reducers.max(this.gain)
        )
        # a vertex with no neighbor in its own community: staying score is
        # -k_x * (tot_cur - k_x) / 2m with k_in = 0
        st_p = comm.select(
            v=this.v,
            _pd=deg.pointer_from(this.v),
            _pc=comm_deg.pointer_from(this.c),
            _pm=m_t.pointer_from(),
            _ps=stay_cand.pointer_from(this.v),
        )
        stay_t = st_p.select(
            v=this.v,
            stay=coalesce(
                stay_cand.ix(st_p._ps, optional=True).stay,
                -coalesce(deg.ix(st_p._pd, optional=True).k, 0.0)
                * (
                    coalesce(comm_deg.ix(st_p._pc, optional=True).tot, 0.0)
                    - coalesce(deg.ix(st_p._pd, optional=True).k, 0.0)
                )
                / (2.0 * m_t.ix(st_p._pm).m),
            ),
        ).with_id_from(this.v)
        # Synchronous moves oscillate (adjacent vertices swap labels), so a
        # vertex moves only if its hash priority beats every neighbor that
        # also wants to move — an independent set of movers, like sequential
        # Louvain's one-at-a-time moves. The globally top-priority mover
        # always qualifies, so progress is guaranteed; when nobody wants to
        # move the state is unchanged and iterate's fixpoint check stops.
        from pathway_tpu.internals.api import ref_scalar
        from pathway_tpu.internals.common import apply_with_type

        flags = comm.select(
            v=this.v,
            p=apply_with_type(
                lambda v: int(ref_scalar(v)) & ((1 << 62) - 1), int, this.v
            ),
            wants=coalesce(b.restrict(comm).best_gain, -1e18)
            > stay_t.restrict(comm).stay + 1e-12,
        ).with_id_from(this.v)
        nb_p = inc0.select(x=this.x, _q=flags.pointer_from(this.y))
        nbr_pri = nb_p.select(
            x=this.x,
            py=if_else(flags.ix(nb_p._q).wants, flags.ix(nb_p._q).p, -1),
        )
        nbr_max = nbr_pri.groupby(this.x).reduce(
            this.x, mx=reducers.max(this.py)
        )
        nm = nbr_max.with_id_from(this.x)
        new_comm = comm.select(
            v=this.v,
            c=if_else(
                flags.restrict(comm).wants
                & (
                    flags.restrict(comm).p
                    > coalesce(nm.restrict(comm).mx, -1)
                ),
                coalesce(b.restrict(comm).best_c, this.c),
                this.c,
            ),
        )
        return new_comm.with_id_from(this.v)

    return iterate(
        lambda comm: step(comm), iteration_limit=iteration_limit, comm=base
    )


def louvain_communities(vertices, edges, iteration_limit: int = 10):
    """Community detection: one-level Louvain on (u, v, weight) edges
    (reference: stdlib/graphs/louvain_communities/). Returns a table keyed
    by vertex with columns (v, c) — c is the community representative."""
    if "weight" not in edges.column_names():
        edges = edges.select(this.u, this.v, weight=1.0)
    return _louvain_one_level(vertices, edges, iteration_limit)
