"""pw.graphs — graph algorithms on tables
(reference: stdlib/graphs/: pagerank, bellman_ford, louvain_communities).
Demonstrates pw.iterate fixed-point computation."""

from __future__ import annotations

from typing import Any

import pathway_tpu.reducers as reducers
from pathway_tpu.internals.common import coalesce, if_else
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.thisclass import this


def pagerank(edges, steps: int = 5, damping: float = 0.85):
    """PageRank over an edge table with columns (u, v): u -> v
    (reference: stdlib/graphs/pagerank/). Returns table keyed by vertex with
    column `rank` (scaled int like the reference's fixed-point ranks)."""
    import pathway_tpu as pw

    out_degree = edges.groupby(edges.u).reduce(
        edges.u, degree=reducers.count()
    )
    vertices_u = edges.groupby(edges.u).reduce(edges.u).select(v=this.u)
    vertices_v = edges.groupby(edges.v).reduce(edges.v).select(v=this.v)
    vertices = (
        vertices_u.concat_reindex(vertices_v)
        .groupby(this.v)
        .reduce(this.v)
    )

    base = vertices.select(v=this.v, rank=1.0)

    def step(ranks):
        deg = out_degree.with_id_from(this.u)
        r = ranks.with_id_from(this.v)
        contribs = edges.select(
            src=edges.u,
            dst=edges.v,
        )
        with_rank = contribs.select(
            dst=this.dst,
            contrib=r.ix(contribs.select(
                _p=ranks.pointer_from(this.src)
            )._p, optional=True).rank
            / deg.ix(contribs.select(
                _p=out_degree.pointer_from(this.src)
            )._p, optional=True).degree,
        )
        summed = with_rank.groupby(this.dst).reduce(
            v=this.dst, incoming=reducers.sum(this.contrib)
        )
        joined = ranks.select(v=this.v).with_id_from(this.v)
        s2 = summed.with_id_from(this.v)
        new_ranks = joined.select(
            v=this.v,
            rank=(1 - damping)
            + damping * coalesce(s2.restrict(joined).incoming, 0.0),
        )
        return new_ranks.with_id_from(this.v)

    ranks = base.with_id_from(this.v)
    result = iterate(
        lambda ranks: step(ranks), iteration_limit=steps, ranks=ranks
    )
    return result


def bellman_ford(vertices, edges):
    """Shortest paths from vertices where is_source=True over edges
    (u, v, dist) (reference: stdlib/graphs/bellman_ford/)."""
    import math

    import pathway_tpu as pw

    base = vertices.select(
        dist_from_source=if_else(
            this.is_source, 0.0, math.inf
        )
    )

    def step(state):
        relaxed = edges.join(
            state, edges.u == state.id
        ).select(
            v=edges.v,
            dist=state.dist_from_source + edges.dist,
        )
        best = relaxed.groupby(this.v).reduce(
            best=reducers.min(this.dist), v=this.v
        ).with_id(this.v)
        new_state = state.select(
            dist_from_source=if_else(
                best.restrict(state).best.is_not_none()
                & (coalesce(best.restrict(state).best, math.inf)
                   < this.dist_from_source),
                coalesce(best.restrict(state).best, math.inf),
                this.dist_from_source,
            )
        )
        return new_state

    return iterate(lambda state: step(state), state=base)


def louvain_communities(*args, **kwargs):
    raise NotImplementedError(
        "louvain_communities is not implemented yet in pathway_tpu"
    )
