"""IVF (inverted-file) KNN kernels — the scale-out story past HBM-resident
brute force.

Design note (VERDICT r3 item 10): the reference carries usearch HNSW for
sub-linear queries (reference: src/external_integration/
usearch_integration.rs:20). HNSW is a pointer-chasing CPU structure — the
worst possible shape for a TPU. The TPU-native answer is IVF: both of its
stages are MXU matmuls,

  1. coarse quantization: queries x centroids^T  -> top-nprobe clusters
  2. fine scoring:        queries x members^T    -> exact top-k within
     the probed inverted lists

so query cost is O(C·D + (N/C)·nprobe·D) instead of O(N·D), with every
FLOP on the systolic array and no data-dependent pointer walks. Training
is mini-batch Lloyd over a sample — also pure matmuls. For corpora that
fit HBM the exact dense path stays faster (TPU-KNN, arXiv 2206.14286);
IVF is the >HBM / sub-linear tier behind the same DataIndex factory
surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=())
def _assign_impl(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """Nearest centroid per row by L2: argmin ||x - c||^2 via the matmul
    expansion (x·c dominates; norms are rank-1 corrections)."""
    x32 = x.astype(jnp.float32)
    c32 = centroids.astype(jnp.float32)
    dots = x32 @ c32.T  # [n, C] — the MXU stage
    c2 = jnp.sum(c32 * c32, axis=1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=1)


def assign_clusters(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Cluster id per row. Pads the row count to the next power of two so
    jit caches stay bounded while batch sizes vary."""
    n = x.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    pad = 1
    while pad < n:
        pad *= 2
    if pad != n:
        x = np.concatenate([x, np.zeros((pad - n, x.shape[1]), x.dtype)])
    out = np.asarray(_assign_impl(jnp.asarray(x), jnp.asarray(centroids)))
    return out[:n].astype(np.int64)


def train_centroids(
    sample: np.ndarray,
    n_clusters: int,
    n_iters: int = 10,
    seed: int = 0,
) -> np.ndarray:
    """Lloyd's k-means on a sample: random-subset init, matmul assignment,
    segment-sum update. Empty clusters re-seed from random points."""
    rng = np.random.default_rng(seed)
    n = sample.shape[0]
    n_clusters = min(n_clusters, n)
    centroids = sample[rng.choice(n, size=n_clusters, replace=False)].astype(
        np.float32
    )
    for _ in range(n_iters):
        assign = assign_clusters(sample, centroids)
        sums = np.zeros_like(centroids)
        np.add.at(sums, assign, sample.astype(np.float32))
        counts = np.bincount(assign, minlength=n_clusters).astype(np.float32)
        empty = counts == 0
        counts[empty] = 1.0
        centroids = sums / counts[:, None]
        if empty.any():
            centroids[empty] = sample[
                rng.choice(n, size=int(empty.sum()), replace=False)
            ]
    return centroids


class IvfDeviceIndex:
    """Device-resident IVF for corpora where brute force is too slow:
    the corpus is permuted into cluster-sorted order at build time, so a
    probed cluster is ONE contiguous HBM range — queries gather nprobe
    ranges, pad to a bucketed static length, and run one fine-scoring
    matmul + top-k per bucket size (static shapes: no recompiles beyond
    the handful of buckets). Both stages are MXU matmuls; there are no
    data-dependent pointer walks (design note at module top; reference
    counterpart: usearch HNSW, usearch_integration.rs:20).

    ``spill`` stores each point in its `spill` nearest lists (ScaNN-style
    multi-assignment): boundary points — where IVF loses its recall on
    unstructured data — then appear in every nearby probe, trading `spill`x
    index memory for recall at fixed n_probe.
    """

    def __init__(
        self,
        corpus: np.ndarray,
        metric: str = "cosine",
        n_clusters: int | None = None,
        n_probe: int | None = None,
        spill: int = 2,
        train_sample: int = 40000,
        seed: int = 0,
    ):
        if metric not in ("cosine", "dot"):
            raise ValueError(f"IvfDeviceIndex: unsupported metric {metric!r}")
        n, dim = corpus.shape
        self.metric = metric
        self.n = n
        self.n_clusters = n_clusters or max(8, int(round((n**0.5) / 8)) * 8)
        self.n_probe = n_probe or max(1, int(round(self.n_clusters**0.5)))
        rng = np.random.default_rng(seed)
        x = corpus.astype(np.float32)
        if metric == "cosine":
            x = x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-30)
        sample = x[rng.choice(n, size=min(train_sample, n), replace=False)]
        self.centroids = train_centroids(sample, self.n_clusters, seed=seed)
        # batched multi-assignment (each point -> its `spill` nearest
        # centroids), then cluster-sort the replicated corpus
        spill = max(1, min(spill, self.n_clusters))
        self.spill = spill
        assign = np.empty((n, spill), np.int32)
        step = 262_144
        cT = self.centroids.T.astype(np.float32)
        c2 = np.sum(self.centroids.astype(np.float32) ** 2, axis=1)
        for lo in range(0, n, step):
            xs = x[lo : lo + step]
            d = c2[None, :] - 2.0 * (xs @ cT)  # ||c||^2 - 2 x.c (+||x||^2)
            assign[lo : lo + step] = np.argpartition(d, spill - 1, axis=1)[
                :, :spill
            ]
        flat_assign = assign.ravel()
        point_of = np.repeat(np.arange(n, dtype=np.int64), spill)
        perm = np.argsort(flat_assign, kind="stable")
        self.order = point_of[perm]
        sorted_assign = flat_assign[perm]
        self.starts = np.searchsorted(
            sorted_assign, np.arange(self.n_clusters)
        ).astype(np.int64)
        self.ends = np.searchsorted(
            sorted_assign, np.arange(self.n_clusters), side="right"
        ).astype(np.int64)
        self.corpus_dev = jax.device_put(x[self.order])
        self.cent_dev = jax.device_put(self.centroids)
        self._fine = {}  # bucket size -> jitted fine scorer

    def _fine_fn(self, bucket: int):
        fn = self._fine.get(bucket)
        if fn is None:

            def fine(q, idx, valid, k):
                rows = jnp.take(self.corpus_dev, idx, axis=0)
                scores = rows @ q
                scores = jnp.where(valid, scores, -jnp.inf)
                top_s, top_i = jax.lax.top_k(scores, k)
                return top_s, jnp.take(idx, top_i)

            fn = jax.jit(fine, static_argnames=("k",))
            self._fine[bucket] = fn
        return fn

    def query(self, q: np.ndarray, k: int = 10) -> tuple[np.ndarray, np.ndarray]:
        """Top-k (scores, original corpus ids) for one query vector."""
        qv = q.astype(np.float32)
        if self.metric == "cosine":
            qv = qv / (np.linalg.norm(qv) + 1e-30)
        d = self.centroids @ qv
        probes = np.argpartition(-d, self.n_probe - 1)[: self.n_probe]
        spans = [(self.starts[c], self.ends[c]) for c in probes.tolist()]
        # dedupe spilled replicas BY POINT id, or duplicates crowd out
        # top-k slots; keep the first sorted position per point
        pos_all = np.concatenate(
            [np.arange(s, e) for s, e in spans]
        ) if spans else np.zeros(0, np.int64)
        pts = self.order[pos_all]
        _uniq, first = np.unique(pts, return_index=True)
        pos_u = pos_all[first]
        total = len(pos_u)
        bucket = 1 << max(1, (total - 1)).bit_length()  # next power of 2
        idx = np.zeros(bucket, np.int64)
        valid = np.zeros(bucket, bool)
        idx[:total] = pos_u
        valid[:total] = True
        kk = min(k, bucket)  # lax.top_k needs k <= operand length
        top_s, top_pos = self._fine_fn(bucket)(
            jax.device_put(qv), jax.device_put(idx), jax.device_put(valid), kk
        )
        top_s = np.asarray(top_s)
        ids = self.order[np.asarray(top_pos)]
        live = top_s > -np.inf  # drop padding slots when total < k
        return top_s[live], ids[live]
