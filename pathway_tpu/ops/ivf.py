"""IVF (inverted-file) KNN kernels — the scale-out story past HBM-resident
brute force.

Design note (VERDICT r3 item 10): the reference carries usearch HNSW for
sub-linear queries (reference: src/external_integration/
usearch_integration.rs:20). HNSW is a pointer-chasing CPU structure — the
worst possible shape for a TPU. The TPU-native answer is IVF: both of its
stages are MXU matmuls,

  1. coarse quantization: queries x centroids^T  -> top-nprobe clusters
  2. fine scoring:        queries x members^T    -> exact top-k within
     the probed inverted lists

so query cost is O(C·D + (N/C)·nprobe·D) instead of O(N·D), with every
FLOP on the systolic array and no data-dependent pointer walks. Training
is mini-batch Lloyd over a sample — also pure matmuls. For corpora that
fit HBM the exact dense path stays faster (TPU-KNN, arXiv 2206.14286);
IVF is the >HBM / sub-linear tier behind the same DataIndex factory
surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=())
def _assign_impl(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """Nearest centroid per row by L2: argmin ||x - c||^2 via the matmul
    expansion (x·c dominates; norms are rank-1 corrections)."""
    x32 = x.astype(jnp.float32)
    c32 = centroids.astype(jnp.float32)
    dots = x32 @ c32.T  # [n, C] — the MXU stage
    c2 = jnp.sum(c32 * c32, axis=1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=1)


def assign_clusters(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Cluster id per row. Pads the row count to the next power of two so
    jit caches stay bounded while batch sizes vary."""
    n = x.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    pad = 1
    while pad < n:
        pad *= 2
    if pad != n:
        x = np.concatenate([x, np.zeros((pad - n, x.shape[1]), x.dtype)])
    out = np.asarray(_assign_impl(jnp.asarray(x), jnp.asarray(centroids)))
    return out[:n].astype(np.int64)


def train_centroids(
    sample: np.ndarray,
    n_clusters: int,
    n_iters: int = 10,
    seed: int = 0,
) -> np.ndarray:
    """Lloyd's k-means on a sample: random-subset init, matmul assignment,
    segment-sum update. Empty clusters re-seed from random points."""
    rng = np.random.default_rng(seed)
    n = sample.shape[0]
    n_clusters = min(n_clusters, n)
    centroids = sample[rng.choice(n, size=n_clusters, replace=False)].astype(
        np.float32
    )
    for _ in range(n_iters):
        assign = assign_clusters(sample, centroids)
        sums = np.zeros_like(centroids)
        np.add.at(sums, assign, sample.astype(np.float32))
        counts = np.bincount(assign, minlength=n_clusters).astype(np.float32)
        empty = counts == 0
        counts[empty] = 1.0
        centroids = sums / counts[:, None]
        if empty.any():
            centroids[empty] = sample[
                rng.choice(n, size=int(empty.sum()), replace=False)
            ]
    return centroids
