"""Segment reductions on device — vectorized groupby kernels
(TPU-native counterpart of the reference's differential `reduce_abelian`
inner loops, src/engine/dataflow.rs:3113-3400, for the dense-numeric case)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_sum(values: jax.Array, segment_ids: jax.Array, num_segments: int):
    return jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(segment_ids: jax.Array, num_segments: int):
    return jax.ops.segment_sum(
        jnp.ones_like(segment_ids, dtype=jnp.int32),
        segment_ids,
        num_segments=num_segments,
    )


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_mean(values: jax.Array, segment_ids: jax.Array, num_segments: int):
    s = jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)
    c = jax.ops.segment_sum(
        jnp.ones_like(values), segment_ids, num_segments=num_segments
    )
    return s / jnp.maximum(c, 1)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_max(values: jax.Array, segment_ids: jax.Array, num_segments: int):
    return jax.ops.segment_max(values, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_min(values: jax.Array, segment_ids: jax.Array, num_segments: int):
    return jax.ops.segment_min(values, segment_ids, num_segments=num_segments)
