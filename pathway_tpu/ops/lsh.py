"""LSH random-projection hashing on device
(reference: stdlib/ml/classifiers/_lsh.py — bucketed ANN in pure dataflow;
here the projections run as one jitted matmul)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def make_projections(
    dim: int, n_or: int, n_and: int, bucket_length: float, seed: int = 0
):
    rng = np.random.default_rng(seed)
    planes = rng.normal(size=(n_or, n_and, dim)).astype(np.float32)
    offsets = rng.uniform(0, bucket_length, size=(n_or, n_and)).astype(
        np.float32
    )
    return jnp.asarray(planes), jnp.asarray(offsets)


@functools.partial(jax.jit, static_argnames=())
def lsh_buckets(vectors, planes, offsets, bucket_length):
    """vectors [N,D] -> bucket ids [N, n_or] (int32) via E2LSH:
    floor((v·a + b) / w) combined over the AND dimension."""
    proj = jnp.einsum("nd,oad->noa", vectors, planes)
    cells = jnp.floor((proj + offsets[None]) / bucket_length).astype(jnp.int32)
    # combine AND-hashes into one bucket id
    mix = cells.astype(jnp.uint32)
    h = jnp.zeros(mix.shape[:2], dtype=jnp.uint32)
    for i in range(mix.shape[2]):
        h = h * jnp.uint32(1000003) + mix[:, :, i]
    return h.astype(jnp.int32)
