"""Dense top-k KNN on TPU — the MXU-native replacement for the reference's
external index family (reference: src/external_integration/
brute_force_knn_integration.rs:22 ndarray matmul top-k, and
usearch_integration.rs HNSW; pattern: TPU-KNN, arXiv 2206.14286).

Design:
- corpus lives in HBM as a padded [capacity, D] array (+ validity mask) so
  shapes stay static across ticks — no recompilation as documents stream in;
  capacity grows by doubling (each size compiles once).
- scores = queries @ corpus.T runs in bfloat16 on the MXU with f32
  accumulation; invalid slots are masked to -inf before `lax.top_k`.
- multi-chip: corpus rows are sharded over the mesh's 'data' axis via
  shard_map — each device computes a local top-k, candidates are
  all-gathered over ICI and merged with a final top-k (the TPU-KNN
  recall@peak-FLOPs recipe).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class KnnParams:
    metric: str = "cosine"  # cosine | dot | l2sq
    bf16: bool = True


def _scores(
    queries: jax.Array, corpus: jax.Array, metric: str, bf16: bool
) -> jax.Array:
    if metric == "cosine":
        qn = queries / (
            jnp.linalg.norm(queries, axis=-1, keepdims=True) + 1e-30
        )
        cn = corpus / (jnp.linalg.norm(corpus, axis=-1, keepdims=True) + 1e-30)
    else:
        qn, cn = queries, corpus
    if bf16:
        qn = qn.astype(jnp.bfloat16)
        cn = cn.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        qn,
        cn,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if metric == "l2sq":
        q2 = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        c2 = jnp.sum(corpus.astype(jnp.float32) ** 2, axis=-1)
        # negative squared distance so that bigger == closer
        return -(q2 - 2.0 * dots + c2[None, :])
    return dots


def _masked_topk(s: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Exact top-k over [B, N] scores. For large N uses the two-stage
    block decomposition (top-k per 1024-column block, then top-k over the
    block winners) — exact because every global top-k element is within
    the top-k of its own block, and much friendlier to the TPU than one
    monolithic 1M-wide TopK."""
    n = s.shape[-1]
    blk = 1024
    if n >= 64 * blk and k <= blk:
        nblk = (n + blk - 1) // blk
        pad = nblk * blk - n
        if pad:
            s = jnp.pad(s, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        sb = s.reshape(s.shape[0], nblk, blk)
        sc1, ix1 = jax.lax.top_k(sb, k)  # [B, nblk, k]
        gidx = ix1 + (jnp.arange(nblk, dtype=ix1.dtype) * blk)[None, :, None]
        sc2, pos = jax.lax.top_k(sc1.reshape(s.shape[0], -1), k)
        idx = jnp.take_along_axis(gidx.reshape(s.shape[0], -1), pos, axis=1)
        return sc2, idx
    return jax.lax.top_k(s, k)


@functools.partial(jax.jit, static_argnames=("k", "metric", "bf16"))
def dense_topk(
    queries: jax.Array,  # [B, D] f32
    corpus: jax.Array,  # [N, D] f32 (padded)
    valid: jax.Array,  # [N] bool
    k: int,
    metric: str = "cosine",
    bf16: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Returns (scores [B, k] f32, indices [B, k] i32); invalid rows get
    -inf scores and index -1."""
    s = _scores(queries, corpus, metric, bf16)
    s = jnp.where(valid[None, :], s, -jnp.inf)
    scores, idx = _masked_topk(s, k)
    idx = jnp.where(jnp.isfinite(scores), idx, -1)
    return scores, idx


# --- prepared-corpus fast path ---------------------------------------------
# Normalization + bf16 cast of the corpus is O(N*D) — done once per corpus
# change, NOT per query. Per-query work is one [B,D]x[D,N] MXU matmul + topk.


@functools.partial(jax.jit, static_argnames=("metric", "bf16"))
def prepare_corpus(corpus: jax.Array, metric: str, bf16: bool = True):
    """Returns (prep [N,D], c2 [N]) — prep is normalized (cosine) and cast;
    c2 is the squared-norm column needed by l2sq."""
    c2 = jnp.sum(corpus.astype(jnp.float32) ** 2, axis=-1)
    if metric == "cosine":
        prep = corpus / (jnp.linalg.norm(corpus, axis=-1, keepdims=True) + 1e-30)
    else:
        prep = corpus
    if bf16:
        prep = prep.astype(jnp.bfloat16)
    return prep, c2


@functools.partial(jax.jit, static_argnames=("k", "metric", "bf16"))
def dense_topk_prepared(
    queries: jax.Array,  # [B, D] f32
    prep: jax.Array,  # [N, D] prepared (normalized/cast)
    c2: jax.Array,  # [N] squared norms (l2sq only)
    valid: jax.Array,  # [N] bool
    k: int,
    metric: str = "cosine",
    bf16: bool = True,
) -> tuple[jax.Array, jax.Array]:
    if metric == "cosine":
        q = queries / (jnp.linalg.norm(queries, axis=-1, keepdims=True) + 1e-30)
    else:
        q = queries
    if bf16:
        q = q.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        q, prep, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if metric == "l2sq":
        q2 = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        s = -(q2 - 2.0 * dots + c2[None, :])
    else:
        s = dots
    s = jnp.where(valid[None, :], s, -jnp.inf)
    scores, idx = _masked_topk(s, k)
    idx = jnp.where(jnp.isfinite(scores), idx, -1)
    return scores, idx


def cosine_topk(queries, corpus, valid, k):
    return dense_topk(queries, corpus, valid, k, metric="cosine")


def shard_base_indices(n: int, n_shards: int) -> np.ndarray:
    """Per-row base offset of its shard (local->global index mapping in the
    sharded merge); single source for sharded_topk and the multi-process
    sharded_topk_global."""
    per = n // n_shards
    return (np.arange(n) // per * per).astype(np.int32)


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "bf16", "mesh", "axis")
)
def _sharded_topk_impl(queries, corpus, valid, base_idx, k, metric, bf16, mesh, axis):
    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.collectives import _shard_map_compat

    shard_map, check_kw = _shard_map_compat()

    def local(q, c, v, b):
        s = _scores(q, c, metric, bf16)
        s = jnp.where(v[None, :], s, -jnp.inf)
        kk = min(k, c.shape[0])
        sc, ix = _masked_topk(s, kk)
        ix = ix + b[0]  # local -> global row index
        # gather candidates from all shards over ICI, merge with final top-k
        sc_all = jax.lax.all_gather(sc, axis, axis=1, tiled=True)
        ix_all = jax.lax.all_gather(ix, axis, axis=1, tiled=True)
        sc_f, pos = jax.lax.top_k(sc_all, k)
        ix_f = jnp.take_along_axis(ix_all, pos, axis=1)
        ix_f = jnp.where(jnp.isfinite(sc_f), ix_f, -1)
        return sc_f, ix_f

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis, None), P(axis), P(axis)),
        out_specs=(P(), P()),
        **check_kw,
    )(queries, corpus, valid, base_idx)


def sharded_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    *,
    mesh: Any,
    axis: str = "data",
    metric: str = "cosine",
    bf16: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Multi-chip KNN: corpus sharded over ``axis``; queries replicated;
    local top-k per shard + all-gather merge (TPU-KNN pattern)."""
    n = corpus.shape[0]
    n_shards = mesh.shape[axis]
    assert n % n_shards == 0, "pad corpus to a multiple of the shard count"
    base_idx = shard_base_indices(n, n_shards)
    return _sharded_topk_impl(
        queries, corpus, valid, jnp.asarray(base_idx), k, metric, bf16, mesh, axis
    )


class DeviceCorpus:
    """Growable padded corpus living on device.

    Host keeps a float32 mirror; the device array is refreshed lazily per
    tick (one host→device transfer per changed tick, amortized over all
    queries in that tick). Capacity doubles ⇒ O(log N) distinct compiled
    shapes."""

    def __init__(
        self,
        dim: int,
        capacity: int = 1024,
        sharding: Any = None,
        valid_sharding: Any = None,
    ):
        self.valid_sharding = valid_sharding
        self.dim = dim
        # align capacity to lcm(1024, n_shards): multiple of 1024 so the
        # Pallas block kernel (ops/pallas_topk.py, BLK=1024) is always
        # applicable, AND divisible by the mesh shard count so sharded_topk
        # can split rows evenly; padding is masked by `valid`
        align = 1024
        if sharding is not None:
            import math

            n_dev = int(np.prod(list(sharding.mesh.shape.values())))
            align = math.lcm(1024, max(1, n_dev))
        self._align = align
        self.capacity = -(-max(1024, capacity) // align) * align
        self.host = np.zeros((self.capacity, dim), dtype=np.float32)
        self.valid_host = np.zeros(self.capacity, dtype=bool)
        self.free: list[int] = list(range(self.capacity - 1, -1, -1))
        self.slot_of: dict[int, int] = {}  # row key -> slot
        self.key_of: dict[int, int] = {}  # slot -> row key
        self._dirty = True
        self._device: jax.Array | None = None
        self._device_valid: jax.Array | None = None
        self._prepared: dict[tuple[str, bool], tuple[jax.Array, jax.Array]] = {}
        self.sharding = sharding

    def __len__(self) -> int:
        return len(self.slot_of)

    def upsert(self, key: int, vector: np.ndarray) -> None:
        slot = self.slot_of.get(key)
        if slot is None:
            if not self.free:
                self._grow()
            slot = self.free.pop()
            self.slot_of[key] = slot
            self.key_of[slot] = key
        self.host[slot] = vector
        self.valid_host[slot] = True
        self._dirty = True

    def remove(self, key: int) -> None:
        slot = self.slot_of.pop(key, None)
        if slot is None:
            return
        self.key_of.pop(slot, None)
        self.valid_host[slot] = False
        self.free.append(slot)
        self._dirty = True

    def _grow(self) -> None:
        old_cap = self.capacity
        self.capacity *= 2
        host = np.zeros((self.capacity, self.dim), dtype=np.float32)
        host[:old_cap] = self.host
        self.host = host
        valid = np.zeros(self.capacity, dtype=bool)
        valid[:old_cap] = self.valid_host
        self.valid_host = valid
        self.free.extend(range(self.capacity - 1, old_cap - 1, -1))
        self._dirty = True

    def device_arrays(self) -> tuple[jax.Array, jax.Array]:
        if self._dirty or self._device is None:
            if self.sharding is not None:
                self._device = jax.device_put(self.host, self.sharding)
                self._device_valid = jax.device_put(
                    self.valid_host, self.valid_sharding
                )
            else:
                self._device = jnp.asarray(self.host)
                self._device_valid = jnp.asarray(self.valid_host)
            self._prepared.clear()
            self._dirty = False
        return self._device, self._device_valid

    def prepared_arrays(
        self, metric: str, bf16: bool = True
    ) -> tuple[jax.Array, jax.Array, jax.Array]:
        """(prep, c2, valid) with normalization/cast amortized across
        queries — refreshed only when the corpus changed."""
        device, valid = self.device_arrays()
        key = (metric, bf16)
        if key not in self._prepared:
            self._prepared[key] = prepare_corpus(device, metric, bf16)
        prep, c2 = self._prepared[key]
        return prep, c2, valid

    def keys_for_slots(self, slots: np.ndarray) -> list[int | None]:
        return [
            self.key_of.get(int(s)) if s >= 0 else None for s in slots
        ]
