"""Pallas TPU kernel: ragged paged-attention for batched decode.

The generation plane (pathway_tpu/generate/) keeps every sequence's KV
state in fixed-size pages of a shared block pool, with a per-sequence
page table mapping logical page index -> physical page id (PAPERS.md,
Ragged Paged Attention, https://arxiv.org/pdf/2604.15464).  One decode
step asks, for each sequence b in the batch, attention of ONE query
token against that sequence's first ``seq_lens[b]`` cached tokens — a
ragged read over scattered pages, which is exactly what the
scalar-prefetch grid is for: the page table is prefetched into SMEM and
the KV block index_map reads it, so grid step (b, j) stages sequence
b's j-th logical page (one [H, P, Dp] tile) into VMEM without ever
materializing a gathered [B, L, H, Dp] tensor in HBM.

Layout honors the Mosaic (8, 128) tiling rule the same way the
pallas_topk fix did (the BENCH_r02 lesson: interpret-green is NOT
lowerable-green):

* pools are ``[n_pages, H, P, Dp]`` with ``Dp = head_dim`` padded up to
  a 128-lane multiple (``lane_pad``); the padded tail lanes are zero in
  both q and k so dot products are unchanged, and v's zero tail keeps
  the output padding zero;
* every block's last two dims are (P, Dp) / (H, Dp): each either
  divides (8, 128) or equals the corresponding array dim —
  ``validate_lowering`` asserts this statically via the shared
  ``check_tpu_block_rules`` so tests gate lowering without TPU
  hardware.

Softmax over the ragged length is the standard online (flash) rescale
across grid steps j — running max/denominator live in VMEM scratch, the
unnormalized accumulator in a third scratch, and the output block is
written once at the last page.  Fully-masked slots (padded batch rows,
seq_len 0) use a large-negative finite mask value instead of -inf so
the rescale never produces NaN; their denominator stays 0 and the
final write zero-fills them.

``paged_attention_ref`` is the jitted pure-JAX twin — the CPU/interpret
fallback the decode step uses off-TPU and the differential oracle the
tests pin the kernel against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# shared 8x128 gate: analysis/lowering.py is the single source of truth
# for the Mosaic tiling rules (re-exported for existing callers)
from pathway_tpu.analysis.lowering import (  # noqa: F401
    LoweringRuleViolation,
    RULE_LANE_PAD,
    check_block_specs,
    check_tpu_block_rules,
    lane_pad,
)

# mask value for invalid key positions: large-negative finite (an -inf
# mask makes the online-softmax rescale NaN on fully-masked pages)
_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _specs(b: int, h: int, p: int, dp: int, n_pages: int, max_pages: int):
    """(grid, in_specs, out_specs, out_shape) for the decode kernel —
    the single source for the kernel's layout, shared by the caller and
    the static lowering gate so they cannot drift apart.  Index maps
    take the scalar-prefetch refs (page_tables, seq_lens) after the
    grid indices."""
    grid = (b, max_pages)
    in_specs = [
        # q: one sequence's single query token, all heads
        (
            pl.BlockSpec((1, h, dp), lambda i, j, pt, sl: (i, 0, 0)),
            (b, h, dp),
        ),
        # k/v: the physical page the sequence's j-th logical page maps
        # to — the ragged indirection lives entirely in this index_map
        (
            pl.BlockSpec(
                (1, h, p, dp), lambda i, j, pt, sl: (pt[i, j], 0, 0, 0)
            ),
            (n_pages, h, p, dp),
        ),
        (
            pl.BlockSpec(
                (1, h, p, dp), lambda i, j, pt, sl: (pt[i, j], 0, 0, 0)
            ),
            (n_pages, h, p, dp),
        ),
    ]
    out_specs = [
        (
            pl.BlockSpec((1, h, dp), lambda i, j, pt, sl: (i, 0, 0)),
            (b, h, dp),
        )
    ]
    out_shape = jax.ShapeDtypeStruct((b, h, dp), jnp.float32)
    return grid, in_specs, out_specs, out_shape


def validate_lowering(
    b: int, h: int, p: int, dp: int, n_pages: int, max_pages: int
) -> None:
    """Assert every block spec the kernel will use satisfies the Mosaic
    TPU rule — the compiled-mode test gate (pallas_topk precedent)."""
    if dp % 128 != 0:
        raise LoweringRuleViolation(
            RULE_LANE_PAD,
            f"head_dim pool width {dp} is not lane-padded (multiple of "
            f"128); pad with lane_pad() — got lane_pad={lane_pad(dp)}",
        )
    grid, in_specs, out_specs, _ = _specs(b, h, p, dp, n_pages, max_pages)
    check_block_specs(in_specs + out_specs)


def _decode_kernel(
    p: int,
    sm_scale: float,
    pt_ref,  # scalar-prefetch: [B, max_pages] page table
    sl_ref,  # scalar-prefetch: [B] sequence lengths
    q_ref,  # [1, H, Dp]
    k_ref,  # [1, H, P, Dp]
    v_ref,  # [1, H, P, Dp]
    o_ref,  # [1, H, Dp]
    m_scr,  # [H, 128] running max (all lanes equal)
    l_scr,  # [H, 128] running denominator (all lanes equal)
    acc_scr,  # [H, Dp] unnormalized output accumulator
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    h, dp = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full((h, 128), _NEG, jnp.float32)
        l_scr[:] = jnp.zeros((h, 128), jnp.float32)
        acc_scr[:] = jnp.zeros((h, dp), jnp.float32)

    q = q_ref[0].astype(jnp.float32)  # [H, Dp]
    k = k_ref[0].astype(jnp.float32)  # [H, P, Dp]
    v = v_ref[0].astype(jnp.float32)
    # per-head scores of the query against this page: [H, P].  Unrolled
    # over heads as 2-D dots — Mosaic only lowers 2-D dot_general (a
    # batched [H,Dp]x[H,P,Dp] contraction is interpret-green but fails
    # TPU lowering; the ledger's AOT export proves this shape)
    s_rows = []
    for hh in range(h):
        s_rows.append(
            jax.lax.dot_general(
                q[hh : hh + 1, :],
                k[hh],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
    s = jnp.concatenate(s_rows, axis=0) * sm_scale
    # ragged mask: token index j*P + col vs this sequence's length
    pos = j * p + jax.lax.broadcasted_iota(jnp.int32, (1, p), 1)
    valid = pos < sl_ref[b]  # [1, P]
    s = jnp.where(valid, s, _NEG)

    m_prev = m_scr[:]  # [H, 128]
    l_prev = l_scr[:]
    m_cur = jnp.max(s, axis=1, keepdims=True)  # [H, 1]
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, (h, 128)))
    alpha = jnp.exp(m_prev - m_new)  # [H, 128] rescale of the old state
    # exp weights for this page, hard-zeroed on masked lanes (on a
    # fully-masked page m_new stays _NEG and exp(s - m_new) would be 1)
    w = jnp.exp(s - m_new[:, :1]) * valid.astype(jnp.float32)  # [H, P]
    l_new = l_prev * alpha + jnp.broadcast_to(
        jnp.sum(w, axis=1, keepdims=True), (h, 128)
    )
    # weighted page values, same per-head 2-D unroll: [H, Dp]
    pv_rows = []
    for hh in range(h):
        pv_rows.append(
            jax.lax.dot_general(
                w[hh : hh + 1, :],
                v[hh],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
    pv = jnp.concatenate(pv_rows, axis=0)
    acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv
    m_scr[:] = m_new
    l_scr[:] = l_new

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]  # [H, 1]
        # fully-masked slots (padded batch rows) have l == 0: zero-fill
        o = jnp.where(l > 0.0, acc_scr[:] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0] = o


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "interpret")
)
def paged_attention(
    q: jax.Array,  # [B, H, Dp] f32 query tokens (padded lanes zero)
    k_pool: jax.Array,  # [n_pages, H, P, Dp]
    v_pool: jax.Array,  # [n_pages, H, P, Dp]
    page_tables: jax.Array,  # [B, max_pages] int32 physical page ids
    seq_lens: jax.Array,  # [B] int32 valid tokens per sequence
    *,
    sm_scale: float,
    interpret: bool = False,
) -> jax.Array:
    """One ragged paged-attention decode step: [B, H, Dp] outputs."""
    b, h, dp = q.shape
    n_pages, _h, p, _dp = k_pool.shape
    max_pages = page_tables.shape[1]
    grid, in_specs, out_specs, out_shape = _specs(
        b, h, p, dp, n_pages, max_pages
    )
    kernel = functools.partial(_decode_kernel, p, float(sm_scale))
    try:
        from jax.experimental.pallas import tpu as pltpu

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[spec for spec, _ in in_specs],
            out_specs=out_specs[0][0],
            scratch_shapes=[
                pltpu.VMEM((h, 128), jnp.float32),
                pltpu.VMEM((h, 128), jnp.float32),
                pltpu.VMEM((h, dp), jnp.float32),
            ],
        )
    except ImportError:  # pragma: no cover - pallas TPU frontend absent
        raise NotImplementedError(
            "pallas TPU grid spec unavailable; use paged_attention_ref"
        ) from None
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        page_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        q.astype(jnp.float32),
        k_pool,
        v_pool,
    )


@jax.jit
def paged_attention_ref(
    q: jax.Array,  # [B, H, Dp]
    k_pool: jax.Array,  # [n_pages, H, P, Dp]
    v_pool: jax.Array,
    page_tables: jax.Array,  # [B, max_pages]
    seq_lens: jax.Array,  # [B]
    *,
    sm_scale: float | jax.Array = 1.0,
) -> jax.Array:
    """Jitted pure-JAX twin — gathers each sequence's pages dense and
    runs a masked softmax.  The CPU/interpret fallback of the decode
    step and the differential oracle for the Pallas kernel."""
    b, h, dp = q.shape
    _n, _h, p, _dp = k_pool.shape
    max_pages = page_tables.shape[1]
    k = k_pool[page_tables]  # [B, max_pages, H, P, Dp]
    v = v_pool[page_tables]
    k = k.transpose(0, 2, 1, 3, 4).reshape(b, h, max_pages * p, dp)
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, h, max_pages * p, dp)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32), k) * sm_scale
    pos = jnp.arange(max_pages * p, dtype=jnp.int32)
    mask = pos[None, None, :] < seq_lens[:, None, None]
    s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    w = jnp.exp(s - m) * mask  # hard-zero the masked tail
    l = jnp.sum(w, axis=-1, keepdims=True)
    out = jnp.einsum("bhl,bhld->bhd", w, v) / jnp.maximum(l, 1e-30)
    return jnp.where(l > 0.0, out, 0.0)
