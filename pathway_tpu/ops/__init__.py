"""pathway_tpu.ops — jitted XLA/Pallas kernels for the engine's hot paths.

This package is the TPU-native replacement for the reference's native
compute: ndarray matmul (src/mat_mul.rs), the external index family
(src/external_integration/ — USearch HNSW / brute-force KNN / Tantivy BM25)
and the per-row expression interpreter's heavy numeric ops. Everything here is
pure jax — jit once, run per microbatch tick.
"""

from pathway_tpu.ops.knn import (
    KnnParams,
    cosine_topk,
    dense_topk,
    sharded_topk,
)
from pathway_tpu.ops.segment import segment_count, segment_mean, segment_sum

__all__ = [
    "KnnParams",
    "dense_topk",
    "cosine_topk",
    "sharded_topk",
    "segment_sum",
    "segment_count",
    "segment_mean",
]
