"""Pallas TPU kernel: fused KNN scoring + block-local top-k.

The hot op of the retrieval path (reference: the brute-force KNN inner
loop, src/external_integration/brute_force_knn_integration.rs:22, here
mapped onto the MXU): for each grid step one [BLK, D] corpus tile is
staged in VMEM, scored against the [B, D] queries on the MXU, masked, and
reduced to the tile's top-k (k max/argmax/suppress passes on the VPU) —
so only [B, nblk*k] candidates ever return to HBM instead of the full
[B, N] score matrix. A final lax.top_k merges block winners (exact, same
argument as ops/knn._masked_topk). Runs in interpreter mode off-TPU so
tests cover it on the CPU backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLK = 1024


def _topk_block_kernel(k: int, q_ref, c_ref, valid_ref, sc_ref, ix_ref):
    # q: [B, D] f32/bf16; c: [BLK, D]; valid: [1, BLK] f32 (1.0/0.0)
    q = q_ref[:]
    c = c_ref[:]
    s = jax.lax.dot_general(
        q,
        c,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, BLK]
    s = jnp.where(valid_ref[:] > 0.5, s, -jnp.inf)
    b = s.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)

    def body(i, carry):
        s_cur, _sc, _ix = carry
        m = jnp.max(s_cur, axis=1)  # [B]
        is_max = s_cur == m[:, None]
        # first column attaining the max
        a = jnp.min(jnp.where(is_max, cols, BLK), axis=1).astype(jnp.int32)
        sc = _sc.at[:, i].set(m)
        ix = _ix.at[:, i].set(a)
        suppress = cols == a[:, None]
        s_next = jnp.where(suppress, -jnp.inf, s_cur)
        return s_next, sc, ix

    sc0 = jnp.full((b, k), -jnp.inf, jnp.float32)
    ix0 = jnp.zeros((b, k), jnp.int32)
    _s, sc, ix = jax.lax.fori_loop(0, k, body, (s, sc0, ix0))
    sc_ref[:] = sc[:, None, :]
    ix_ref[:] = ix[:, None, :]


@functools.partial(
    jax.jit, static_argnames=("k", "interpret")
)
def pallas_block_topk(
    queries: jax.Array,  # [B, D]
    prep: jax.Array,  # [N, D] prepared corpus (N multiple of BLK)
    valid: jax.Array,  # [N] bool
    k: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Per-block candidates: ([B, nblk, k] scores, [B, nblk, k] global
    indices)."""
    bq, d = queries.shape
    n = prep.shape[0]
    assert n % BLK == 0, "pad the corpus to a multiple of BLK"
    nblk = n // BLK
    validf = valid.astype(jnp.float32).reshape(1, n)
    kernel = functools.partial(_topk_block_kernel, k)
    sc, ix = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((bq, d), lambda i: (0, 0)),
            pl.BlockSpec((BLK, d), lambda i: (i, 0)),
            pl.BlockSpec((1, BLK), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((bq, 1, k), lambda i: (0, i, 0)),
            pl.BlockSpec((bq, 1, k), lambda i: (0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bq, nblk, k), jnp.float32),
            jax.ShapeDtypeStruct((bq, nblk, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries, prep, validf)
    # local -> global indices
    ix = ix + (jnp.arange(nblk, dtype=jnp.int32) * BLK)[None, :, None]
    return sc, ix


@functools.partial(jax.jit, static_argnames=("k", "metric", "interpret"))
def pallas_dense_topk(
    queries: jax.Array,  # [B, D] raw f32 queries
    prep: jax.Array,  # [N, D] prepared corpus (normalized/cast)
    valid: jax.Array,
    k: int,
    metric: str = "dot",  # dot | cosine
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Exact dense top-k via the Pallas block kernel + lax.top_k merge.
    Owns the query-side metric handling (normalize + cast to the corpus
    dtype) so every caller scores identically to dense_topk_prepared."""
    if metric == "cosine":
        queries = queries / (
            jnp.linalg.norm(queries, axis=-1, keepdims=True) + 1e-30
        )
    queries = queries.astype(prep.dtype)
    sc, ix = pallas_block_topk(queries, prep, valid, k, interpret=interpret)
    b = sc.shape[0]
    sc_f = sc.reshape(b, -1)
    ix_f = ix.reshape(b, -1)
    scores, pos = jax.lax.top_k(sc_f, k)
    idx = jnp.take_along_axis(ix_f, pos, axis=1)
    idx = jnp.where(jnp.isfinite(scores), idx, -1)
    return scores, idx


def supported(n: int, k: int) -> bool:
    return n % BLK == 0 and k <= BLK


def _kernel_out_block_fix():  # pragma: no cover - doc anchor
    """Out specs use a singleton middle dim so each grid step owns its
    [B, 1, k] slice of the [B, nblk, k] outputs."""
