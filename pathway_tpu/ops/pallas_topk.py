"""Pallas TPU kernel: fused KNN scoring + block-local top-k.

The hot op of the retrieval path (reference: the brute-force KNN inner
loop, src/external_integration/brute_force_knn_integration.rs:22, here
mapped onto the MXU): for each grid step one [BLK, D] corpus tile is
staged in VMEM, scored against the [B, D] queries on the MXU, masked, and
reduced to the tile's top-k (k max/argmax/suppress passes on the VPU) —
so only [B, nblk*KP] candidates ever return to HBM instead of the full
[B, N] score matrix. A final lax.top_k merges block winners (exact, same
argument as ops/knn._masked_topk). Runs in interpreter mode off-TPU so
tests cover it on the CPU backend.

TPU lowering constraint (the round-2 failure): the last two dims of every
block must be divisible by (8, 128) or equal the overall array dims. The
outputs are therefore laid out 2-D as [B, nblk*KP] where KP = k padded up
to a multiple of 128 — each grid step writes its own lane-aligned (B, KP)
tile (KP % 128 == 0; B equals the array dim), with the real k winners in
the leading lanes and -inf/0 padding after. The caller reshapes to
[B, nblk, KP] and slices [..., :k]. `check_tpu_block_rules` asserts the
constraint statically so tests gate it without TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# the 8x128 rules live in ONE place (analysis/lowering.py) — re-exported
# here for the existing test gates and callers
from pathway_tpu.analysis.lowering import (  # noqa: F401
    check_block_specs,
    check_tpu_block_rules,
    lane_pad,
)

BLK = 1024


def _kpad(k: int) -> int:
    """k padded up to the TPU lane width (multiple of 128)."""
    return lane_pad(k)


def _specs(bq: int, d: int, n: int, k: int):
    """(grid, in_specs, out_specs, out_shapes, nblk, kp) for the block-
    top-k call — the single source for the kernel's layout, shared by the
    caller and the static test gate so they can't drift apart."""
    nblk = n // BLK
    kp = _kpad(k)
    in_specs = [
        (pl.BlockSpec((bq, d), lambda i: (0, 0)), (bq, d)),
        (pl.BlockSpec((BLK, d), lambda i: (i, 0)), (n, d)),
        (pl.BlockSpec((1, BLK), lambda i: (0, i)), (1, n)),
    ]
    out_specs = [
        (pl.BlockSpec((bq, kp), lambda i: (0, i)), (bq, nblk * kp)),
        (pl.BlockSpec((bq, kp), lambda i: (0, i)), (bq, nblk * kp)),
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((bq, nblk * kp), jnp.float32),
        jax.ShapeDtypeStruct((bq, nblk * kp), jnp.int32),
    ]
    return (nblk,), in_specs, out_specs, out_shapes, nblk, kp


def _topk_block_kernel(k: int, kp: int, q_ref, c_ref, valid_ref, sc_ref, ix_ref):
    # q: [B, D] f32/bf16; c: [BLK, D]; valid: [1, BLK] f32 (1.0/0.0)
    q = q_ref[:]
    c = c_ref[:]
    s = jax.lax.dot_general(
        q,
        c,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, BLK]
    s = jnp.where(valid_ref[:] > 0.5, s, -jnp.inf)
    b = s.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # float copy for the argmax reduction: Mosaic has no integer
    # reduce_min lowering, and BLK (< 2^24) is exact in f32
    colsf = cols.astype(jnp.float32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (b, kp), 1)

    def body(i, carry):
        s_cur, _sc, _ix = carry
        m = jnp.max(s_cur, axis=1)  # [B]
        is_max = s_cur == m[:, None]
        # first column attaining the max
        a = jnp.min(
            jnp.where(is_max, colsf, float(BLK)), axis=1
        ).astype(jnp.int32)
        # one-hot lane write (dynamic per-lane .at[] scatters lower poorly
        # on the VPU; a masked select vectorizes)
        hit = out_cols == i
        sc = jnp.where(hit, m[:, None], _sc)
        ix = jnp.where(hit, a[:, None], _ix)
        suppress = cols == a[:, None]
        s_next = jnp.where(suppress, -jnp.inf, s_cur)
        return s_next, sc, ix

    sc0 = jnp.full((b, kp), -jnp.inf, jnp.float32)
    ix0 = jnp.zeros((b, kp), jnp.int32)
    _s, sc, ix = jax.lax.fori_loop(0, k, body, (s, sc0, ix0))
    sc_ref[:] = sc
    ix_ref[:] = ix


@functools.partial(
    jax.jit, static_argnames=("k", "interpret")
)
def pallas_block_topk(
    queries: jax.Array,  # [B, D]
    prep: jax.Array,  # [N, D] prepared corpus (N multiple of BLK)
    valid: jax.Array,  # [N] bool
    k: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Per-block candidates: ([B, nblk, k] scores, [B, nblk, k] global
    indices)."""
    bq, d = queries.shape
    n = prep.shape[0]
    assert n % BLK == 0, "pad the corpus to a multiple of BLK"
    validf = valid.astype(jnp.float32).reshape(1, n)
    grid, in_specs, out_specs, out_shapes, nblk, kp = _specs(bq, d, n, k)
    kernel = functools.partial(_topk_block_kernel, k, kp)
    sc, ix = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec for spec, _ in in_specs],
        out_specs=[spec for spec, _ in out_specs],
        out_shape=out_shapes,
        interpret=interpret,
    )(queries, prep, validf)
    sc = sc.reshape(bq, nblk, kp)[:, :, :k]
    ix = ix.reshape(bq, nblk, kp)[:, :, :k]
    # local -> global indices
    ix = ix + (jnp.arange(nblk, dtype=jnp.int32) * BLK)[None, :, None]
    return sc, ix


@functools.partial(jax.jit, static_argnames=("k", "metric", "interpret"))
def pallas_dense_topk(
    queries: jax.Array,  # [B, D] raw f32 queries
    prep: jax.Array,  # [N, D] prepared corpus (normalized/cast)
    valid: jax.Array,
    k: int,
    metric: str = "dot",  # dot | cosine
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Exact dense top-k via the Pallas block kernel + lax.top_k merge.
    Owns the query-side metric handling (normalize + cast to the corpus
    dtype) so every caller scores identically to dense_topk_prepared."""
    if metric == "cosine":
        queries = queries / (
            jnp.linalg.norm(queries, axis=-1, keepdims=True) + 1e-30
        )
    queries = queries.astype(prep.dtype)
    sc, ix = pallas_block_topk(queries, prep, valid, k, interpret=interpret)
    b = sc.shape[0]
    sc_f = sc.reshape(b, -1)
    ix_f = ix.reshape(b, -1)
    scores, pos = jax.lax.top_k(sc_f, k)
    idx = jnp.take_along_axis(ix_f, pos, axis=1)
    idx = jnp.where(jnp.isfinite(scores), idx, -1)
    return scores, idx


def supported(n: int, k: int) -> bool:
    return n % BLK == 0 and k <= BLK


def validate_lowering(bq: int, d: int, n: int, k: int) -> None:
    """Assert every block spec the kernel will use satisfies the TPU
    lowering rule. Used by the compiled-mode test gate."""
    _grid, in_specs, out_specs, _shapes, _nblk, _kp = _specs(bq, d, n, k)
    check_block_specs(in_specs + out_specs)
