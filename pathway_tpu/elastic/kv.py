"""Generation-plane resharding: KV ledgers ride the same ferry.

The decode scheduler's in-flight state — KV pages + resumable sequence
metadata — already lives in arrangement ledgers keyed by the sequence's
jk hash (generate/kv_cache.py), which is exactly the ownership function
the rest of the system reshards by.  ``split_kv_store`` re-partitions a
generation member's snapshot directory into per-new-owner snapshot
directories: each new owner's ``DecodeScheduler(store_root=...,
restore=True)`` then RESUMES the in-flight decodes it now owns, token
streams continuing bit-identically (greedy/seeded sampling is
deterministic, the restore path is the kill/restore machinery PR 14
already pinned).  A destination given as a ferry endpoint receives its
snapshot over the authenticated SegmentFerry wire (per-segment MACs,
resume) — the new owner can live on another host.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Any

import numpy as np

from pathway_tpu.elastic.handover import HandoverError
from pathway_tpu.engine.sharded import shard_of
from pathway_tpu.generate.kv_cache import KvLedger, seq_jk


def seq_owner(seq_id: int, n_shards: int) -> int:
    """The shard owning one in-flight sequence — the sequence's ledger
    jk (``kv_cache.seq_jk``) through the system-wide jk-hash
    partition, so generation ownership agrees with every other plane."""
    jk = np.asarray([seq_jk(seq_id)], dtype=np.uint64)
    return int(shard_of(jk, n_shards)[0])


def split_ledger(led: KvLedger, n_new: int) -> list[KvLedger]:
    """Split one KV ledger's live state into one ledger per new owner.
    Rebuilt through the mirror API, so each part is consolidated (only
    live pages/seqs — a handoff never ferries retracted history)."""
    parts = [KvLedger() for _ in range(n_new)]
    for seq_id, meta in led.live_seqs().items():
        parts[seq_owner(seq_id, n_new)].put_seq(seq_id, dict(meta))
    for (seq_id, page_idx), cols in led.live_pages().items():
        k_page, v_page = cols[0], cols[1]
        parts[seq_owner(seq_id, n_new)].put_page(
            seq_id, page_idx, np.array(k_page), np.array(v_page)
        )
    return parts


def _snapshot_files(root: str) -> list[tuple[str, bytes]]:
    files = []
    for base, _dirs, names in os.walk(root):
        for f in names:
            full = os.path.join(base, f)
            rel = os.path.relpath(full, root)
            with open(full, "rb") as fh:
                files.append((rel, fh.read()))
    return files


def split_kv_store(
    src_root: str,
    destinations: list[Any],
    *,
    transfer_id: str | None = None,
) -> dict:
    """Re-partition a generation snapshot directory into per-owner
    stores (index = new shard).  Each destination is either a local
    directory path (written directly — the same-filesystem O(copy)
    path) or a ``(host, port)`` ferry endpoint whose
    :class:`~pathway_tpu.elastic.ferry.FerryReceiver` roots the remote
    owner's store.  Raises when ``src_root`` holds no snapshot."""
    from pathway_tpu.elastic.ferry import ferry_files

    led = KvLedger.restore(src_root)
    if led is None:
        raise HandoverError(
            f"{src_root} holds no committed generation snapshot"
        )
    n_new = len(destinations)
    parts = split_ledger(led, n_new)
    tid = transfer_id or f"kv-reshard-{n_new}"
    out: dict[str, Any] = {"n_new": n_new, "destinations": []}
    moved_bytes = 0
    for p, (part, dest) in enumerate(zip(parts, destinations)):
        n_seqs = len(part.live_seqs())
        ferry = None
        if isinstance(dest, (tuple, list)):
            host, port = dest
            tmp = tempfile.mkdtemp(prefix="pw-kv-ferry-")
            try:
                stats = part.snapshot(tmp)
                ferry = ferry_files(
                    host,
                    int(port),
                    _snapshot_files(tmp),
                    transfer_id=f"{tid}-p{p}",
                )
                moved_bytes += ferry["bytes_sent"]
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            where = f"{host}:{port}"
        else:
            os.makedirs(dest, exist_ok=True)
            stats = part.snapshot(dest)
            where = str(dest)
        out["destinations"].append(
            {
                "dest": where,
                "seqs": n_seqs,
                "snapshot": stats,
                "ferry": ferry,
            }
        )
    out["total_seqs"] = len(led.live_seqs())
    out["bytes_ferried"] = moved_bytes
    return out
