"""ReshardPlanner — the hash-ring delta of an N→M topology change.

Ownership is the jk-hash partition every tier already routes by
(engine/sharded.py ``shard_of``: ``(jk & SHARD_MASK) % n_shards``), so
the unit of movement is a *slot* — one residue of the 65536-value
low-16-bit key space.  An N→M change moves exactly the slots whose
``% N`` and ``% M`` owners differ; everything else stays put.  The plan
is the minimal set of (src, dst, slots) key-range moves, and
:func:`split_arrangement` / :func:`repartition_arrangements` realize it
on arrangement state: consolidated rows re-split by their jk's new
owner, moved ranges encoded as fresh sealed segments (the PR-7 codec)
ready for the ferry, unmoved ranges never re-encoded for the wire.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass

import numpy as np

from pathway_tpu.engine.arrangement import Arrangement
from pathway_tpu.engine.sharded import SHARD_MASK, shard_of

SLOT_SPACE = SHARD_MASK + 1  # 65536 hash slots — the routing residue space


def slot_owners(n_shards: int) -> np.ndarray:
    """owner shard of every slot under an ``n_shards`` topology."""
    return (
        np.arange(SLOT_SPACE, dtype=np.uint64) % np.uint64(n_shards)
    ).astype(np.int32)


@dataclass(frozen=True)
class KeyRangeMove:
    """One key range changing hands: the slots moving src → dst."""

    src: int
    dst: int
    n_slots: int


@dataclass(frozen=True)
class ReshardPlan:
    """The minimal moves of an N→M change (slots whose owner differs)."""

    n_old: int
    n_new: int
    moves: tuple[KeyRangeMove, ...]

    @property
    def moved_slots(self) -> int:
        return sum(m.n_slots for m in self.moves)

    @property
    def moved_fraction(self) -> float:
        return self.moved_slots / SLOT_SPACE


def plan_reshard(n_old: int, n_new: int) -> ReshardPlan:
    """Compute the hash-ring delta: for every (src, dst) pair with
    src != dst, how many slots move.  ``moved_fraction`` is the share
    of the key space (and so, for uniform keys, of state bytes) the
    ferry must carry — never the full corpus unless n_old == 1."""
    if n_old < 1 or n_new < 1:
        raise ValueError(
            f"shard counts must be >= 1 (got {n_old} -> {n_new})"
        )
    old = slot_owners(n_old)
    new = slot_owners(n_new)
    moving = old != new
    moves: dict[tuple[int, int], int] = {}
    for s, d in zip(old[moving].tolist(), new[moving].tolist()):
        moves[(s, d)] = moves.get((s, d), 0) + 1
    return ReshardPlan(
        n_old,
        n_new,
        tuple(
            KeyRangeMove(s, d, n)
            for (s, d), n in sorted(moves.items())
        ),
    )


def moved_fraction(n_old: int, n_new: int) -> float:
    return plan_reshard(n_old, n_new).moved_fraction


# --- arrangement-level re-partition -----------------------------------------


def _rows_to_arrangement(rows, idx: np.ndarray, n_cols: int) -> Arrangement:
    """Fresh sealed arrangement holding ``rows.take(idx)``, appended in
    age order so the new arrangement's emission order preserves the
    source's insertion order (GroupBy restore, dedup acceptance and
    last-write-wins state all read it)."""
    out = Arrangement(n_cols)
    if len(idx):
        sub = rows.take(idx[np.argsort(rows.age[idx], kind="stable")])
        out.append(sub.jk, sub.key, sub.count, sub.cols)
        out.seal()
    return out


def split_arrangement(
    arr: Arrangement, n_new: int
) -> list[Arrangement]:
    """Split one arrangement's consolidated state into one arrangement
    per new shard, rows routed by ``shard_of(jk, n_new)``."""
    rows = arr.entries()
    if not len(rows):
        return [Arrangement(arr.n_cols) for _ in range(n_new)]
    dest = shard_of(np.asarray(rows.jk, dtype=np.uint64), n_new)
    return [
        _rows_to_arrangement(
            rows, np.nonzero(dest == s)[0], arr.n_cols
        )
        for s in range(n_new)
    ]


def repartition_arrangements(
    per_shard: list[dict[str, Arrangement]], n_new: int
) -> tuple[list[dict[str, Arrangement]], dict]:
    """Re-partition N shards' named arrangements into M shards' — the
    core state move.  Rows of the same arrangement NAME merge across
    the old shards, then split by their jk's new owner; relative age
    order within each (old shard, name) is preserved and old shards are
    concatenated in shard order (disjoint jk ranges per old shard make
    the cross-shard interleave irrelevant to consolidated state).

    Returns (new per-shard dicts, stats) where stats counts total vs
    MOVED rows — moved = rows whose old owner index differs from their
    new one, the "bytes ferried ≈ moved key ranges only" evidence."""
    n_old = len(per_shard)
    names: list[str] = []
    for d in per_shard:
        for name in d:
            if name not in names:
                names.append(name)
    out: list[dict[str, Arrangement]] = [{} for _ in range(n_new)]
    total_rows = 0
    moved_rows = 0
    for name in names:
        parts = []  # (old_shard, Rows)
        n_cols = None
        for old_s, d in enumerate(per_shard):
            arr = d.get(name)
            if arr is None:
                continue
            n_cols = arr.n_cols
            rows = arr.entries()
            if len(rows):
                parts.append((old_s, rows))
        if n_cols is None:
            continue
        per_dst_chunks: list[list] = [[] for _ in range(n_new)]
        for old_s, rows in parts:
            total_rows += len(rows)
            dest = shard_of(np.asarray(rows.jk, dtype=np.uint64), n_new)
            # a row is "moved" when its new owner differs from the old
            # shard that held it — exactly the slot plan's owner change
            moved_rows += int(np.count_nonzero(dest != old_s))
            for dst in range(n_new):
                idx = np.nonzero(dest == dst)[0]
                if not len(idx):
                    continue
                sub = rows.take(
                    idx[np.argsort(rows.age[idx], kind="stable")]
                )
                per_dst_chunks[dst].append(sub)
        for dst in range(n_new):
            arr = Arrangement(n_cols)
            for sub in per_dst_chunks[dst]:
                arr.append(sub.jk, sub.key, sub.count, sub.cols)
            arr.seal()
            out[dst][name] = arr
    return out, {
        "total_rows": total_rows,
        "moved_rows": moved_rows,
        "moved_row_fraction": (
            moved_rows / total_rows if total_rows else 0.0
        ),
    }


def repartition_shard_states(
    residuals: list[dict],
    per_shard_arrs: list[dict[str, Arrangement]],
    n_new: int,
) -> tuple[list[dict], list[dict[str, Arrangement]], dict]:
    """The ``_ShardedExec`` restore transform: an N-shard snapshot's
    (per-shard residuals, per-shard arrangements) re-partitioned for an
    M-shard layout.  Keyed state lives in the arrangements (every
    arranged exec rebuilds its dicts FROM them on load); residuals
    carry only per-exec config/watermark scalars identical across
    shards, so each new shard receives a deep copy of shard 0's."""
    new_arrs, stats = repartition_arrangements(per_shard_arrs, n_new)
    base = residuals[0] if residuals else {}
    new_residuals = [copy.deepcopy(base) for _ in range(n_new)]
    return new_residuals, new_arrs, stats


# --- reshard capability (Graph Doctor support) ------------------------------


def exec_class_for(node) -> type | None:
    """The exec class a node builds, resolved by the ``FooNode`` →
    ``FooExec`` naming convention inside the node's own module (every
    engine node follows it); None when the convention does not
    resolve."""
    import sys

    mod = sys.modules.get(type(node).__module__)
    name = type(node).__name__
    if mod is None or not name.endswith("Node"):
        return None
    cls = getattr(mod, name[:-4] + "Exec", None)
    return cls if isinstance(cls, type) else None


def reshard_capable(node) -> bool | None:
    """Whether this node's exec snapshots as arrangements (and so can
    ride a segment handoff instead of pinning the group to log-replay
    resizes).  None = unknown (no exec class resolved)."""
    from pathway_tpu.engine.nodes import NodeExec

    cls = exec_class_for(node)
    if cls is None:
        return None
    fn = getattr(cls, "arranged_state", None)
    return fn is not None and fn is not NodeExec.arranged_state


def monolithic_state_nodes(nodes) -> list[tuple]:
    """[(node, exec class name)] for every stateful node whose exec
    provably lacks ``arranged_state`` — the operators that pin a Shard
    Flux resize to log-replay (ROADMAP 5c). The elastic plane's
    metadata hook for static verification: the Plane Doctor's
    snapshot-coverage rule (analysis/plane.py) names these before
    anyone attempts a resize against them."""
    out = []
    for node in nodes:
        if not getattr(node, "is_stateful", False):
            continue
        if reshard_capable(node) is not False:
            continue
        cls = exec_class_for(node)
        out.append((node, cls.__name__ if cls else type(node).__name__))
    return out
