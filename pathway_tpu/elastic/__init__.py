"""Shard Flux — live elastic resharding: move state, not logs.

Every stateful tier of this system partitions keyed state by ONE
ownership function (engine/sharded.py ``shard_of``: the low 16 bits of
the jk hash mod the shard count), and every tier's durable form is the
content-addressed arrangement segment (persistence/segments.py).  This
package exploits both facts to change a topology's shard/rank count
WITHOUT replaying the input log:

* :mod:`planner` — ``ReshardPlanner``: the hash-ring delta of an N→M
  change (which key slots move, between whom) and the arrangement-level
  row re-partition that realizes it.
* :mod:`ferry` — ``SegmentFerry``: streams whole arrangement segments
  to their new owners over the PWHX-family authenticated wire, with
  per-segment integrity MACs and content-addressed resumable transfer.
* :mod:`handover` — the two-phase handover barrier: freeze a migrating
  topology at a tick boundary, commit the new ownership map under a
  bumped incarnation (zombies fenced by the existing incarnation
  checks), unfreeze — bounded pause, zero replay, rollback on any
  failure before the commit point.
* :mod:`mesh` — ``reshard_stores``: the DCN compute-mesh plane — an
  N-rank group's per-rank persistence stores re-partitioned into M
  per-rank stores (only moved key ranges cross rank boundaries), driven
  by ``GroupSupervisor.resize``.
* :mod:`kv` — the generation plane: the KV ledger's page arrangements
  ride the same split, so in-flight decodes resume on their new owner.

Fault Forge's ``kill=ferry:N`` directive (testing/faults.py) kills a
process deterministically on the ferry's segment-transfer counter, so
chaos tests can assert the barrier rolls back cleanly mid-handoff.
"""

from pathway_tpu.elastic.ferry import FerryReceiver, ferry_files
from pathway_tpu.elastic.handover import (
    OwnershipMap,
    TwoPhaseHandover,
    load_ownership,
)
from pathway_tpu.elastic.planner import (
    KeyRangeMove,
    ReshardPlan,
    exec_class_for,
    moved_fraction,
    plan_reshard,
    repartition_arrangements,
    repartition_shard_states,
    reshard_capable,
    split_arrangement,
)

__all__ = [
    "FerryReceiver",
    "KeyRangeMove",
    "OwnershipMap",
    "ReshardPlan",
    "TwoPhaseHandover",
    "exec_class_for",
    "ferry_files",
    "load_ownership",
    "moved_fraction",
    "plan_reshard",
    "repartition_arrangements",
    "repartition_shard_states",
    "reshard_capable",
    "split_arrangement",
]
