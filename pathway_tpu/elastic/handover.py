"""Two-phase handover — freeze, move, commit the new ownership map.

The resharding contract every plane shares:

1. **Freeze** — the migrating topology stops mutating at a tick
   boundary (the mesh plane stops the supervised group at a lockstep
   commit point; the serving plane's writer holds its shard split; the
   generation plane snapshots at a decode-step boundary).  The durable
   committed ownership map stays the OLD one.
2. **Transfer** — the planner's moved key ranges ship via the
   SegmentFerry (or O(mmap) store re-partition when src and dst share
   a filesystem).  A death anywhere in this phase leaves the old map
   committed: restart simply serves the old topology (rollback = do
   nothing), and a retried transfer resumes content-addressed.
3. **Commit** — the new map is published atomically under a BUMPED
   incarnation.  Every consumer that fences by incarnation today
   (PWRP2 subacks, supervisor restarts, Fault Forge directives) fences
   zombies of the old topology for free: a writer/rank still speaking
   the pre-reshard map presents a lower incarnation and is rejected.
4. **Unfreeze** — the new topology resumes from the moved state with
   zero replay.

``OwnershipMap`` is the durable artifact; ``TwoPhaseHandover`` drives
the phases over a directory (the persistence-store root of the plane
being resharded).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass

_COMMITTED = "ownership.json"
_TRANSITION = "ownership.next.json"


@dataclass(frozen=True)
class OwnershipMap:
    """The committed shard topology of one plane: who owns the jk-hash
    key space, under which fencing incarnation."""

    n_shards: int
    incarnation: int
    status: str = "committed"  # committed | transition

    def to_json(self) -> str:
        return json.dumps(asdict(self), separators=(",", ":"))


def _map_path(root: str, name: str) -> str:
    return os.path.join(root, "reshard", name)


def load_ownership(root: str) -> OwnershipMap | None:
    """The last COMMITTED ownership map under ``root`` (transition
    markers are invisible here by design — a torn handover must leave
    readers on the old map)."""
    path = _map_path(root, _COMMITTED)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return OwnershipMap(
        int(doc["n_shards"]), int(doc["incarnation"]), "committed"
    )


class HandoverError(RuntimeError):
    pass


class TwoPhaseHandover:
    """Drives one reshard of one plane rooted at ``root``.

    ``begin(n_new)`` writes the transition marker (phase 1 is the
    caller's freeze — this records intent durably so an operator can
    see a reshard was in flight); ``commit()`` atomically replaces the
    committed map with the new topology under a bumped incarnation;
    ``rollback()`` removes the marker and leaves the old map untouched.
    A crash at ANY point before ``commit``'s atomic rename leaves the
    old committed map in force."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "reshard"), exist_ok=True)

    @property
    def committed(self) -> OwnershipMap | None:
        return load_ownership(self.root)

    @property
    def in_transition(self) -> bool:
        return os.path.exists(_map_path(self.root, _TRANSITION))

    def ensure_committed(self, n_shards: int) -> OwnershipMap:
        """Bootstrap: commit the CURRENT topology if no map exists yet
        (a plane that has never resharded is implicitly committed at
        its boot shard count, incarnation 0)."""
        cur = self.committed
        if cur is not None:
            return cur
        m = OwnershipMap(int(n_shards), 0)
        self._write(_COMMITTED, m)
        return m

    def begin(self, n_new: int) -> OwnershipMap:
        cur = self.committed
        if cur is None:
            raise HandoverError(
                "no committed ownership map — call ensure_committed() "
                "with the current topology first"
            )
        if self.in_transition:
            raise HandoverError(
                "a handover is already in transition — commit or roll "
                "it back first"
            )
        nxt = OwnershipMap(int(n_new), cur.incarnation + 1, "transition")
        self._write(_TRANSITION, nxt)
        return nxt

    def commit(self) -> OwnershipMap:
        path = _map_path(self.root, _TRANSITION)
        if not os.path.exists(path):
            raise HandoverError("no handover in transition to commit")
        with open(path) as f:
            doc = json.load(f)
        m = OwnershipMap(int(doc["n_shards"]), int(doc["incarnation"]))
        # the commit point: one atomic rename — before it the old map
        # rules, after it the new one does, never anything in between
        os.replace(path, _map_path(self.root, _COMMITTED))
        return m

    def rollback(self) -> None:
        try:
            os.unlink(_map_path(self.root, _TRANSITION))
        except FileNotFoundError:
            pass

    def _write(self, name: str, m: OwnershipMap) -> None:
        path = _map_path(self.root, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(m.to_json())
        os.replace(tmp, path)
