"""Mesh-plane resharding: N per-rank stores → M per-rank stores.

A supervised DCN group persists one store per rank (the job script
keys it by PATHWAY_PROCESS_ID), each holding that rank's disjoint
jk-range of every arranged exec's state.  ``reshard_stores`` is the
transfer phase of a group resize: it loads the newest group-committed
generation from every old rank's store, re-partitions every
arrangement's rows by the NEW rank count (engine/sharded.py
``shard_of`` at process level, and the inner device-shard level when
the snapshot is device-sharded too), and writes a fresh generation
into every new rank's store — so the respawned M-rank group restores
with ``replayed_events == 0``.  Only rows whose rank changes are
"moved"; with ``via_wire=True`` the moved ranges additionally ship
through a real :class:`~pathway_tpu.elastic.ferry.FerryReceiver`
per destination (per-segment MACs, content-addressed resume, the
Fault Forge ``kill=ferry:N`` clock), which is also the bytes-ferried
evidence the bench records.  Same-filesystem deployments may set
``via_wire=False`` for a pure O(mmap+put) transform.

Non-arranged (monolithic) snapshots cannot be re-partitioned: kept
ranks carry theirs forward verbatim, grown ranks start those execs
fresh, and the Graph Doctor's ``elastic-resharding`` rule warns ahead
of time about stateful execs this pins to log-replay resizes.

Residual caveats: per-exec residuals hold config/watermark scalars
(identical across ranks — new ranks take rank 0's); a DCN return-home
wrapper's origin tracker maps row keys to OLD rank ids and is reset —
origins rebuild as rows flow.
"""

from __future__ import annotations

import copy
import json
import pickle
from typing import Any

from pathway_tpu.elastic.handover import HandoverError
from pathway_tpu.engine.dcn import DCN_EXTRA_KEY, DCN_INNER_KEY
from pathway_tpu.elastic.planner import plan_reshard
from pathway_tpu.engine.arrangement import Arrangement
from pathway_tpu.engine.sharded import shard_of
from pathway_tpu.persistence._runtime_glue import (
    _META_KEY,
    segment_key,
    state_key,
)
from pathway_tpu.persistence.backends import FilesystemStore
from pathway_tpu.persistence.segments import (
    load_arrangement,
    manifest_of,
    segment_to_bytes,
)


def _seg_copy(seg):
    """Shallow segment copy (arrays shared, identity reset) — the
    whole-segment fast path: a segment whose entire slot range lands on
    one new owner ships without any row decode or re-partition."""
    from pathway_tpu.engine.arrangement import _Segment

    return _Segment(
        seg.jks,
        seg.keys,
        seg.diffs,
        seg.ages,
        list(seg.cols),
        seg.mix_sorted,
        seg.clean,
        -1,
    )


def _seg_slice(seg, idx):
    """Row subset of a sealed segment for a straddler split.  ``idx``
    is increasing, so jk sort order survives; ORIGINAL ages ride along
    so every (jk, key) history keeps its relative order in the
    destination arrangement."""
    import numpy as np

    from pathway_tpu.engine.arrangement import _Segment, mix_keys

    jks = seg.jks[idx]
    keys = seg.keys[idx]
    diffs = seg.diffs[idx]
    mix_sorted = np.sort(mix_keys(jks, keys))
    # a subset of a clean segment is clean (insert-only survives
    # subsetting and duplicates cannot appear); otherwise recompute
    clean = bool(seg.clean) or (
        bool((diffs > 0).all())
        and not bool((mix_sorted[1:] == mix_sorted[:-1]).any())
    )
    return _Segment(
        jks,
        keys,
        diffs,
        seg.ages[idx],
        [np.asarray(c)[idx] for c in seg.cols],
        mix_sorted,
        clean,
        -1,
    )


def _arr_from_pieces(n_cols: int, pieces: list) -> Arrangement:
    """Destination arrangement assembled from shipped segment pieces
    (whole segments and straddler slices, source order).  Ages continue
    from the largest shipped age; cross-source age overlap is harmless
    because old ranks hold disjoint jk ranges."""
    arr = Arrangement(n_cols)
    if not pieces:
        return arr
    next_age = 0
    neg = 0
    for seg in pieces:
        seg.seg_id = arr._alloc_seg_id()
        next_age = max(next_age, int(seg.ages.max()) + 1)
        neg += int((seg.diffs < 0).sum())
    arr.segments = list(pieces)
    arr._next_age = next_age
    arr._entries = int(sum(len(s) for s in pieces))
    arr._neg_entries = neg
    return arr


def _choose_generation(meta: dict, group_time: int) -> dict | None:
    """The newest generation at or below the group-agreed time (the
    same newest-first walk group recovery performs)."""
    candidates = [meta.get("state")]
    candidates += [
        r.get("state")
        for r in reversed(meta.get("retained_states", []))
        if r.get("state")
    ]
    if meta.get("prev_state"):
        candidates.append(meta["prev_state"])
    for cand in candidates:
        if cand and int(cand.get("time", 0)) <= group_time:
            return cand
    return None


def _unwrap(residual: dict, arrs: dict) -> tuple[bool, Any, bool, list, list]:
    """Peel the DCN-wrapper and device-shard nesting off one rank's
    arranged blob → (dcn_wrapped, dcn_extra, dev_sharded,
    [per-dev residual], [per-dev {bare name: Arrangement}])."""
    dcn = isinstance(residual, dict) and DCN_INNER_KEY in residual
    extra = residual.get(DCN_EXTRA_KEY, {}) if dcn else None
    inner = residual[DCN_INNER_KEY] if dcn else residual
    if isinstance(inner, dict) and "__shard_residuals__" in inner:
        dev_res = list(inner["__shard_residuals__"])
        per: list[dict] = [{} for _ in dev_res]
        for key, arr in arrs.items():
            shard, _, name = key.partition(".")
            per[int(shard[1:])][name] = arr
        return dcn, extra, True, dev_res, per
    return dcn, extra, False, [inner], [dict(arrs)]


def _wrap(
    dcn: bool,
    extra: Any,
    dev_sharded: bool,
    dev_res: list,
    per_dev: list[dict],
) -> tuple[dict, dict]:
    """Inverse of :func:`_unwrap` for one NEW rank's blob."""
    if dev_sharded:
        inner_res: Any = {"__shard_residuals__": dev_res}
        arrs = {
            f"s{d}.{name}": arr
            for d, named in enumerate(per_dev)
            for name, arr in named.items()
        }
    else:
        inner_res = dev_res[0]
        arrs = dict(per_dev[0])
    if dcn:
        # origin trackers map row keys to OLD rank ids: reset, rebuild
        new_extra = dict(extra or {})
        if "origin" in new_extra:
            new_extra["origin"] = {}
        return (
            {DCN_INNER_KEY: inner_res, DCN_EXTRA_KEY: new_extra},
            arrs,
        )
    return inner_res, arrs


def reshard_stores(
    old_roots: list[str],
    new_roots: list[str],
    *,
    via_wire: bool = True,
    transfer_id: str | None = None,
) -> dict:
    """Re-partition N per-rank stores into M — the mesh transfer phase.

    Raises :class:`HandoverError` (leaving every store untouched up to
    the metadata commit, i.e. rollback-able) when a retired rank still
    holds log events no snapshot covers, or when a store has no
    restorable generation at the group-agreed time."""
    import time as _time

    from pathway_tpu.elastic.ferry import FerryReceiver, ferry_files

    _t0 = _time.monotonic()
    n_old, n_new = len(old_roots), len(new_roots)
    if n_old < 1 or n_new < 1:
        raise HandoverError("resharding needs >= 1 store on both sides")
    plan = plan_reshard(n_old, n_new)
    stores = [FilesystemStore(r) for r in old_roots]
    metas = []
    for i, st in enumerate(stores):
        raw = st.get(_META_KEY)
        if raw is None:
            raise HandoverError(
                f"old rank {i} ({old_roots[i]}) has no persistence "
                "metadata — nothing to reshard"
            )
        metas.append(json.loads(raw.decode()))
    group_time = min(
        int((m.get("state") or {}).get("time", -1)) for m in metas
    )
    if group_time < 0:
        raise HandoverError(
            "no group-committed operator-state generation exists yet — "
            "resharding moves state, not logs"
        )
    # fixpoint: every rank's CHOSEN generation must sit at ONE agreed
    # time (the retained-generation walk may land a rank below the
    # first minimum when the exact group_time generation was not
    # retained) — stamping a time the state does not actually cover
    # would skip replaying the gap's log events silently
    for _ in range(len(metas) + 2):
        snaps = [_choose_generation(m, group_time) for m in metas]
        if any(s is None for s in snaps):
            raise HandoverError(
                f"some rank cannot restore the group time {group_time}"
            )
        chosen_min = min(int(s["time"]) for s in snaps)
        if chosen_min == group_time:
            break
        group_time = chosen_min
    else:
        raise HandoverError(
            "no generation time is restorable on every rank"
        )
    # shrink guard: a retired rank's uncovered log tail has no new home
    for r in range(n_new, n_old):
        m = metas[r]
        tail = any(v for v in m.get("live_chunks", {}).values())
        if tail or int(m.get("last_time", 0)) > int(snaps[r]["time"]):
            raise HandoverError(
                f"rank {r} retires but holds log events newer than its "
                f"snapshot (time {m.get('last_time')} > "
                f"{snaps[r]['time']}) — snapshot before resizing down"
            )

    # --- load + re-partition every node -----------------------------------
    idents: list[str] = []
    for s in snaps:
        for ident in s.get("nodes", {}):
            if ident not in idents:
                idents.append(ident)
    new_gen = max(int(s["gen"]) for s in snaps) + 1
    # per new rank: {ident: (cls, blob, [(segment key, bytes, moved)])}
    out_nodes: list[dict[str, tuple[str, bytes, list]]] = [
        {} for _ in range(n_new)
    ]
    total_rows = 0
    moved_rows = 0
    bytes_total = 0
    bytes_moved = 0
    # segment-level split accounting: intact = sealed segments whose
    # whole slot range moves to ONE new owner (shipped without a row
    # decode), split = straddlers sliced row-wise, kept = segments that
    # stay home untouched
    segments_shipped_intact = 0
    segments_split = 0
    segments_kept = 0
    # per new rank: the cross-rank chunks as sealed segment blobs —
    # the bytes that genuinely travel (and the FerryReceiver payload)
    moved_blobs: list[list[tuple[str, bytes]]] = [
        [] for _ in range(len(new_roots))
    ]
    monolithic: list[str] = []
    for ident in idents:
        cls = next(
            s["nodes"][ident] for s in snaps if ident in s.get("nodes", {})
        )
        ranks: list[tuple[int, dict]] = []
        mono_blobs: dict[int, bytes] = {}
        for r, (st, s) in enumerate(zip(stores, snaps)):
            if ident not in s.get("nodes", {}):
                continue
            if s["nodes"][ident] != cls:
                raise HandoverError(
                    f"node {ident} class differs across ranks "
                    f"({cls} vs {s['nodes'][ident]})"
                )
            raw = st.get(state_key(int(s["gen"]), ident))
            if raw is None:
                raise HandoverError(
                    f"rank {r}: state blob for node {ident} missing"
                )
            state = pickle.loads(raw)
            if not (
                isinstance(state, dict) and state.get("__pw_arranged__")
            ):
                mono_blobs[r] = raw
                continue
            arrs = {}
            for name, man in state["manifests"].items():
                arrs[name] = load_arrangement(
                    man,
                    lambda sid, name=name, epoch=man["epoch"], ident=ident,
                    st=st: st.get_buffer(
                        segment_key(
                            ident, name, epoch, sid
                        )
                    ),
                )
            ranks.append((r, (state["residual"], arrs)))
        if mono_blobs:
            # monolithic snapshot: carried forward verbatim on kept
            # ranks, fresh on grown ranks (the doctor's
            # elastic-resharding rule warns when such an exec is
            # stateful — it pins key-range moves to log replay)
            monolithic.append(f"{cls}#{ident}")
            for r, raw in mono_blobs.items():
                if r < n_new:
                    out_nodes[r][ident] = (cls, raw, [])
            continue
        if not ranks:
            continue
        dcn, extra, dev_sharded, dev_res0, _ = _unwrap(*ranks[0][1])
        k_dev = len(dev_res0)
        names: list[str] = []
        name_cols: dict[str, int] = {}  # arity survives emptiness: a
        # fully-retracted arrangement must rebuild at its true n_cols
        # gather (old rank, dev shard, name) -> Arrangement; the inner
        # device shard ``shard_of(jk, k_dev)`` is invariant under a
        # process-count change (k_dev is fixed by the job), so segments
        # never cross dev shards and each (r, d) splits independently
        per_rank_arrs: dict[tuple[int, int, str], Arrangement] = {}
        for r, (residual, arrs) in ranks:
            _d, _e, _ds, _res, per_dev = _unwrap(residual, arrs)
            for d, named in enumerate(per_dev):
                for name, arr in named.items():
                    if name not in names:
                        names.append(name)
                    name_cols[name] = arr.n_cols
                    per_rank_arrs[(r, d, name)] = arr
        import numpy as np

        # --- segment-level split ------------------------------------
        # Ownership is decided per SEALED SEGMENT, not per consolidated
        # row: a segment whose every jk hashes to one new owner ships
        # intact (zero-copy views straight off the source mmap — no
        # consolidation pass, no re-append), and only straddlers are
        # sliced row-wise.  Host work is O(moved bytes + straddler
        # bytes) instead of O(total store bytes).  Original ages ride
        # along in both cases so (jk, key) histories keep their
        # relative order and the restored fold stays bit-equal.
        new_pieces: list[list[dict[str, list]]] = [
            [dict() for _ in range(k_dev)] for _ in range(n_new)
        ]
        moved_chunks: list[list[tuple[str, Any]]] = [
            [] for _ in range(n_new)
        ]  # per dst rank: (name, piece) arriving from a DIFFERENT rank
        for (r, d, name), arr in per_rank_arrs.items():
            for seg in arr.segments:
                if not len(seg):
                    continue
                total_rows += len(seg)
                jks = np.asarray(seg.jks, dtype=np.uint64)
                dest = shard_of(jks, n_new)
                owners = np.unique(dest)
                if len(owners) == 1:
                    p = int(owners[0])
                    piece = _seg_copy(seg)
                    if p != r:
                        moved_rows += len(seg)
                        segments_shipped_intact += 1
                        moved_chunks[p].append((name, piece))
                    else:
                        segments_kept += 1
                    new_pieces[p][d].setdefault(name, []).append(piece)
                    continue
                segments_split += 1
                for p in owners.tolist():
                    p = int(p)
                    idx = np.nonzero(dest == p)[0]
                    piece = _seg_slice(seg, idx)
                    if p != r:
                        moved_rows += len(idx)
                        moved_chunks[p].append((name, piece))
                    new_pieces[p][d].setdefault(name, []).append(piece)
        # every name must exist on every dev shard (load_arranged
        # indexes by name), even when empty for this rank — at its
        # SOURCE arity, never a guessed one
        new_per_rank: list[list[dict[str, Arrangement]]] = [
            [
                {
                    name: _arr_from_pieces(
                        name_cols[name], new_pieces[p][d].get(name, [])
                    )
                    for name in names
                }
                for d in range(k_dev)
            ]
            for p in range(n_new)
        ]
        for p in range(n_new):
            # the ferried artifact: each cross-rank piece's segment
            # blob — exactly the moved key ranges' bytes (intact
            # segments re-encode their shared views verbatim)
            for j, (name, piece) in enumerate(moved_chunks[p]):
                blob = segment_to_bytes(piece)
                moved_blobs[p].append(
                    (f"{ident}/{name}/part{j:04d}.seg", blob)
                )
            res_list = [copy.deepcopy(dev_res0[0]) for _ in range(k_dev)]
            residual, arrs = _wrap(
                dcn, extra, dev_sharded, res_list, new_per_rank[p]
            )
            manifests = {}
            seg_files: list[tuple[str, bytes]] = []
            for name, arr in arrs.items():
                man = manifest_of(arr)
                manifests[name] = man
                by_id = {s.seg_id: s for s in arr.segments}
                for sd in man["segments"]:
                    key = segment_key(
                        ident, name, man["epoch"], sd["id"]
                    )
                    blob = segment_to_bytes(by_id[sd["id"]])
                    seg_files.append((key, blob))
            blob = pickle.dumps(
                {
                    "__pw_arranged__": 1,
                    "residual": residual,
                    "manifests": manifests,
                }
            )
            out_nodes[p][ident] = (cls, blob, seg_files)
    # accounting: total = every final segment byte; ferried = only the
    # moved key ranges' chunk segments (what actually crosses ranks)
    for p in range(n_new):
        for _ident, (_cls, _blob, segs) in out_nodes[p].items():
            for _key, data in segs:
                bytes_total += len(data)
        for _name, data in moved_blobs[p]:
            bytes_moved += len(data)

    # --- transfer + write phase -------------------------------------------
    # Two stages across ALL roots, so a failure ANYWHERE in the ferry/
    # data stage leaves every old metadata committed (full rollback —
    # the new-generation files are inert orphans until metadata names
    # them).  Only the final metadata stage — one tiny local JSON put
    # per root — commits the new topology; its window is a few renames,
    # and the driving handover (supervisor resize / TwoPhaseHandover)
    # still brackets the whole thing.
    tid = transfer_id or f"reshard-{n_old}to{n_new}-g{new_gen}"
    # Fleet Lens: reshard phase transitions land in the incident journal
    # (persisted — peers reconstruct a SIGKILLed rank's reshard from
    # these), and /fleet/events derives the reshard window from
    # reshard-transfer -> reshard-commit
    from pathway_tpu.observability.journal import record as journal_record

    journal_record(
        "reshard-transfer",
        f"{n_old} -> {n_new} ranks (generation {new_gen})",
        persist=True,
        n_old=n_old,
        n_new=n_new,
        generation=new_gen,
        group_time=group_time,
        moved_rows=moved_rows,
        bytes_ferried=bytes_moved,
        segments_shipped_intact=segments_shipped_intact,
        segments_split=segments_split,
    )
    ferry_stats: list[dict] = []
    dsts = [FilesystemStore(root) for root in new_roots]
    for p, dst in enumerate(dsts):
        moved_files = moved_blobs[p]
        if via_wire and moved_files:
            recv = FerryReceiver(dst._path("reshard/inbox"))
            try:
                ferry_stats.append(
                    ferry_files(
                        recv.host,
                        recv.port,
                        moved_files,
                        transfer_id=f"{tid}-p{p}",
                    )
                )
            finally:
                recv.close()
        for ident, (cls, blob, segs) in out_nodes[p].items():
            for key, data in segs:
                dst.put(key, data)
            dst.put(state_key(new_gen, ident), blob)
    for p, dst in enumerate(dsts):
        root = new_roots[p]
        nodes_map: dict[str, str] = {}
        segment_keys: list[str] = []
        for ident, (cls, blob, segs) in out_nodes[p].items():
            for key, _data in segs:
                segment_keys.append(key)
            nodes_map[ident] = cls
        raw = dst.get(_META_KEY)
        meta = (
            json.loads(raw.decode())
            if raw is not None
            else {"last_time": 0, "chunks": {}}
        )
        meta["state"] = {
            "gen": new_gen,
            "time": group_time,
            "nodes": nodes_map,
            "segment_keys": sorted(segment_keys),
        }
        meta["last_time"] = max(int(meta.get("last_time", 0)), group_time)
        # superseded generations were partitioned for the OLD topology
        # and must never be restored under the new one — but their
        # inter-snapshot chunk lists may cover log events newer than
        # the agreed group time (a rank whose own snapshot was newer):
        # fold them into live_chunks so the replay can still walk them
        live = {
            pid: list(ids)
            for pid, ids in meta.get("live_chunks", {}).items()
        }
        retained_chunk_maps = [
            r.get("chunks", {}) for r in meta.get("retained_states", [])
        ]
        if meta.get("prev_chunks"):
            retained_chunk_maps.append(meta["prev_chunks"])
        for cmap in retained_chunk_maps:
            for pid, ids in cmap.items():
                merged = list(dict.fromkeys(list(ids) + live.get(pid, [])))
                live[pid] = merged
        meta["live_chunks"] = live
        meta.pop("retained_states", None)
        meta.pop("prev_state", None)
        meta.pop("prev_chunks", None)
        dst.put(_META_KEY, json.dumps(meta).encode())
        # the ferried inbox was the wire transfer itself (and its
        # evidence); the authoritative files are the store keys the
        # metadata now names — drop the staging copy
        import shutil as _shutil

        _shutil.rmtree(dst._path("reshard/inbox"), ignore_errors=True)
    transfer_seconds = _time.monotonic() - _t0
    journal_record(
        "reshard-commit",
        f"{n_old} -> {n_new} ranks committed (generation {new_gen})",
        persist=True,
        n_old=n_old,
        n_new=n_new,
        generation=new_gen,
        bytes_ferried=bytes_moved,
        transfer_seconds=round(transfer_seconds, 6),
    )
    return {
        "plan": {
            "n_old": n_old,
            "n_new": n_new,
            "moved_slot_fraction": round(plan.moved_fraction, 4),
        },
        "generation": new_gen,
        "group_time": group_time,
        "nodes_resharded": len(idents) - len(monolithic),
        "monolithic_carried": monolithic,
        "total_rows": total_rows,
        "moved_rows": moved_rows,
        "segments_shipped_intact": segments_shipped_intact,
        "segments_split": segments_split,
        "segments_kept": segments_kept,
        "bytes_total_segments": bytes_total,
        "bytes_ferried": bytes_moved,
        "transfer_seconds": round(transfer_seconds, 6),
        "ferry": ferry_stats,
    }
