"""SegmentFerry — stream arrangement segments to their new owners.

The transfer rides the PWHX wire family: the same per-job shared-secret
nonce challenge-response as the host mesh and the replication stream
(parallel/host_exchange.py, parallel/replicate.py), length-prefixed
frames each MAC'd over (src, dst, seq, body).  On top of the framed
link every SEGMENT carries its own integrity MAC — HMAC-SHA256 over
(transfer id, blob name, payload) — so a blob staged on disk across a
reconnect is still provably the bytes the sender meant, not just the
bytes the link delivered.

Resumability is content-addressed, like everything else in the State
Ledger lineage: the sender OFFERS the manifest (names + digests), the
receiver answers with what it already staged, and only the missing
blobs cross the wire.  A transfer killed mid-flight (the Fault Forge
``kill=ferry:N`` directive counts segments sent, so chaos tests land
the death deterministically) leaves staged blobs under the transfer's
staging directory; a retry ships only the remainder; ``commit`` moves
the staged set into place atomically per blob and only then reports
success — the two-phase handover (elastic/handover.py) never commits
an ownership map over a half-arrived transfer.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
import threading
from typing import Any

from pathway_tpu.parallel import wire
from pathway_tpu.parallel.host_exchange import (
    _MAC_LEN,
    _NONCE_LEN,
    _REJECT,
    _frame_mac,
    _job_key,
)

_FERRY_MAGIC = b"PWFY1"  # segment-ferry protocol lane (sits beside the
# mesh's PWHX7 and the replication stream's PWRP2: a ferry peer is
# neither a rank nor a subscriber, so it gets its own handshake magic)
_OK_TAG = b"PWFO"
_FERRY_SRC = -7  # reserved src id for ferry frame MACs (never a rank)


class FerryError(RuntimeError):
    pass


def _segment_mac(key: bytes, transfer_id: str, name: str, blob: bytes) -> bytes:
    return hmac.new(
        key, transfer_id.encode() + b"\x00" + name.encode() + b"\x00" + blob,
        "sha256",
    ).digest()


def blob_digest(blob: bytes) -> str:
    """Content address of one ferried blob (resume identity)."""
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def _read_exact(conn: socket.socket, count: int) -> bytes | None:
    buf = b""
    while len(buf) < count:
        try:
            chunk = conn.recv(count - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class _Framed:
    """One authenticated framed link (either side): seq-MAC'd frames of
    pickled control tuples / raw segment payloads."""

    def __init__(self, conn: socket.socket, key: bytes):
        self.conn = conn
        self.key = key
        self.send_seq = 0
        self.recv_seq = 0

    def send(self, frame: tuple) -> None:
        body, _stats = wire.encode_frame(frame, "pickle", None)
        mac = _frame_mac(self.key, _FERRY_SRC, _FERRY_SRC, self.send_seq, body)
        self.send_seq += 1
        self.conn.sendall(struct.pack("<I", len(body)) + mac + body)

    def recv(self) -> tuple | None:
        head = _read_exact(self.conn, 4 + _MAC_LEN)
        if head is None:
            return None
        (length,) = struct.unpack("<I", head[:4])
        body = _read_exact(self.conn, length)
        if body is None:
            return None
        if not hmac.compare_digest(
            head[4:],
            _frame_mac(self.key, _FERRY_SRC, _FERRY_SRC, self.recv_seq, body),
        ):
            return None  # forged/replayed frame: drop the link
        self.recv_seq += 1
        try:
            return wire.decode_frame(body)
        except Exception:
            return None


class FerryReceiver:
    """New-owner side: accepts authenticated transfers into a staging
    area, commits them into ``dest_dir`` on the sender's commit frame.

    ``received`` maps transfer_id -> {name: path} for committed
    transfers; ``staged(transfer_id)`` lists what a torn transfer left
    behind (the resume inventory).  ``abort(transfer_id)`` discards a
    rolled-back transfer's staging."""

    def __init__(self, dest_dir: str, host: str = "127.0.0.1", port: int = 0):
        self.dest_dir = dest_dir
        self._staging = os.path.join(dest_dir, ".ferry-staging")
        os.makedirs(self._staging, exist_ok=True)
        self._key = _job_key()
        self._lock = threading.Lock()
        self.received: dict[str, dict[str, str]] = {}
        self.committed: list[str] = []
        self._closed = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._listener.listen(8)
        threading.Thread(
            target=self._accept_loop, daemon=True, name="pw-ferry-accept"
        ).start()

    # --- staging inventory ------------------------------------------------

    def _stage_dir(self, transfer_id: str) -> str:
        safe = hashlib.blake2b(
            transfer_id.encode(), digest_size=8
        ).hexdigest()
        return os.path.join(self._staging, safe)

    def staged(self, transfer_id: str) -> set[str]:
        """Digests already staged for a transfer (the resume set)."""
        d = self._stage_dir(transfer_id)
        if not os.path.isdir(d):
            return set()
        return {f for f in os.listdir(d) if not f.endswith(".tmp")}

    def abort(self, transfer_id: str) -> None:
        """Roll back: discard everything a torn transfer staged."""
        import shutil

        shutil.rmtree(self._stage_dir(transfer_id), ignore_errors=True)

    # --- wire -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            nonce = os.urandom(_NONCE_LEN)
            conn.settimeout(30.0)
            conn.sendall(nonce)
            hello = _read_exact(conn, len(_FERRY_MAGIC) + _MAC_LEN)
            if hello is None or hello[: len(_FERRY_MAGIC)] != _FERRY_MAGIC:
                conn.close()
                return
            claimed, mac = hello[:-_MAC_LEN], hello[-_MAC_LEN:]
            if not hmac.compare_digest(
                mac, hmac.new(self._key, claimed + nonce, "sha256").digest()
            ):
                try:
                    conn.sendall(_REJECT)
                except OSError:
                    pass
                conn.close()
                return
            conn.sendall(
                hmac.new(
                    self._key, _OK_TAG + nonce + claimed, "sha256"
                ).digest()
            )
            conn.settimeout(None)
            link = _Framed(conn, self._key)
            self._transfer_loop(link)
        except Exception:
            pass  # fail-stop the link; the sender resumes
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _transfer_loop(self, link: _Framed) -> None:
        transfer_id: str | None = None
        manifest: dict[str, str] = {}  # digest -> name
        while True:
            frame = link.recv()
            if frame is None:
                return
            kind = frame[0]
            if kind == "offer":
                # ("offer", transfer_id, [(name, digest), ...])
                _k, transfer_id, entries = frame
                manifest = {dig: name for name, dig in entries}
                os.makedirs(self._stage_dir(transfer_id), exist_ok=True)
                link.send(("have", sorted(self.staged(transfer_id))))
            elif kind == "seg":
                # ("seg", transfer_id, name, digest, payload, seg_mac)
                _k, tid, name, dig, payload, seg_mac = frame
                if tid != transfer_id:
                    return
                expect = _segment_mac(self._key, tid, name, payload)
                if not hmac.compare_digest(seg_mac, expect):
                    return  # tampered segment: drop the link, no ack
                if blob_digest(payload) != dig:
                    return
                path = os.path.join(self._stage_dir(tid), dig)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(payload)
                os.replace(tmp, path)
                link.send(("ack", dig))
            elif kind == "commit":
                # ("commit", transfer_id): every manifest digest staged →
                # move blobs into dest_dir under their offered names
                _k, tid = frame
                if tid != transfer_id:
                    return
                have = self.staged(tid)
                missing = set(manifest) - have
                if missing:
                    link.send(("incomplete", sorted(missing)))
                    continue
                placed: dict[str, str] = {}
                # manifests last: a crash mid-placement must never leave
                # a manifest naming segment files not yet in place
                ordered = sorted(
                    manifest.items(),
                    key=lambda kv: (kv[1].endswith("manifest.json"), kv[1]),
                )
                for dig, name in ordered:
                    final = os.path.join(self.dest_dir, name)
                    os.makedirs(os.path.dirname(final), exist_ok=True)
                    os.replace(
                        os.path.join(self._stage_dir(tid), dig), final
                    )
                    placed[name] = final
                with self._lock:
                    self.received[tid] = placed
                    self.committed.append(tid)
                self.abort(tid)  # clear the (now empty) staging dir
                link.send(("committed", tid))
            else:
                return

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


def ferry_files(
    host: str,
    port: int,
    files: list[tuple[str, bytes]],
    *,
    transfer_id: str,
    connect_timeout: float = 30.0,
    commit: bool = True,
) -> dict[str, Any]:
    """Old-owner side: ship ``files`` (name, blob) to a
    :class:`FerryReceiver` and (by default) commit the transfer.

    Returns stats: segments offered/sent/skipped (resume hits) and
    bytes sent.  The Fault Forge ``kill=ferry:N`` directive fires on
    the deterministic sent-segment counter — BEFORE the commit frame,
    so an injected death always leaves a rollback-able transfer.

    The whole transfer is a ``ferry.transfer`` root span (Fleet Lens:
    the previously-untraced hop of a reshard), carrying the resume
    arithmetic as attributes."""
    from pathway_tpu.observability.tracing import get_tracer

    with get_tracer().span(
        "ferry.transfer",
        root=True,
        transfer_id=transfer_id,
        segments=len(files),
    ) as span:
        stats = _ferry_files(
            host,
            port,
            files,
            transfer_id=transfer_id,
            connect_timeout=connect_timeout,
            commit=commit,
        )
        span.set_attribute("segments_sent", stats["segments_sent"])
        span.set_attribute("segments_resumed", stats["segments_resumed"])
        span.set_attribute("bytes_sent", stats["bytes_sent"])
        return stats


def _ferry_files(
    host: str,
    port: int,
    files: list[tuple[str, bytes]],
    *,
    transfer_id: str,
    connect_timeout: float = 30.0,
    commit: bool = True,
) -> dict[str, Any]:
    from pathway_tpu.testing import faults

    key = _job_key()
    s = socket.create_connection((host, port), timeout=connect_timeout)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(30.0)
        nonce = _read_exact(s, _NONCE_LEN)
        if nonce is None:
            raise FerryError("receiver closed during handshake")
        hello = _FERRY_MAGIC
        s.sendall(hello + hmac.new(key, hello + nonce, "sha256").digest())
        ok = _read_exact(s, _MAC_LEN)
        if ok is None:
            raise FerryError("receiver closed during handshake")
        if ok == _REJECT:
            raise FerryError(
                "ferry receiver rejected the handshake — authentication "
                "failed (is PATHWAY_DCN_SECRET identical on both ends?)"
            )
        expected = hmac.new(key, _OK_TAG + nonce + hello, "sha256").digest()
        if not hmac.compare_digest(ok, expected):
            raise FerryError("unexpected ferry handshake response")
        s.settimeout(None)
        link = _Framed(s, key)
        digests = [(name, blob_digest(blob)) for name, blob in files]
        link.send(("offer", transfer_id, digests))
        frame = link.recv()
        if frame is None or frame[0] != "have":
            raise FerryError("ferry offer was not answered")
        have = set(frame[1])
        plan = faults.active()
        sent = 0
        skipped = 0
        bytes_sent = 0
        for (name, blob), (_n, dig) in zip(files, digests):
            if dig in have:
                skipped += 1
                continue
            link.send(
                (
                    "seg",
                    transfer_id,
                    name,
                    dig,
                    blob,
                    _segment_mac(key, transfer_id, name, blob),
                )
            )
            ack = link.recv()
            if ack is None or ack[0] != "ack" or ack[1] != dig:
                raise FerryError(f"segment {name} was not acknowledged")
            sent += 1
            bytes_sent += len(blob)
            if plan is not None:
                # deterministic chaos clock: fires AFTER the ack, BEFORE
                # any commit — a kill here always leaves a resumable,
                # rollback-able transfer
                plan.on_ferry_segment(sent)
        committed = False
        if commit:
            link.send(("commit", transfer_id))
            frame = link.recv()
            if frame is None:
                raise FerryError("ferry commit was not answered")
            if frame[0] == "incomplete":
                raise FerryError(
                    f"ferry commit refused: missing segments {frame[1]}"
                )
            if frame[0] != "committed":
                raise FerryError(f"unexpected ferry commit reply {frame[0]!r}")
            committed = True
        return {
            "transfer_id": transfer_id,
            "segments_offered": len(files),
            "segments_sent": sent,
            "segments_resumed": skipped,
            "bytes_sent": bytes_sent,
            "committed": committed,
        }
    finally:
        try:
            s.close()
        except OSError:
            pass
